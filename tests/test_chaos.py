"""Chaos matrix: seeded fault injection (raydp_tpu/faults.py) against the
lineage-recovery plane, proving *byte-identical* action results under
failures — not merely "it eventually returned something".

Matrix (ISSUE 3 acceptance criteria):
- executor killed mid-groupagg (between partial and merge)  → task retry
- shuffle bucket blob dropped before the reduce stage       → lineage rebuild
  (and the same schedule with recovery disabled must raise StageError,
  proving the injection actually bites)
- crash during cache() materialization                      → lineage rebuild
  of lost cached blocks on read
- estimator epoch failure                                   → checkpoint resume

Every schedule is pinned with ``nth=`` + a ``once=`` sentinel file, so the
injection is deterministic per session AND observable (the test asserts the
sentinel exists — a schedule that never fired would silently test nothing).
"""

import os
import threading
import time

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

import raydp_tpu
from raydp_tpu import faults
from raydp_tpu.etl import functions as F
from raydp_tpu.etl.engine import StageError
from raydp_tpu.runtime.object_store import ObjectRef


def _ipc_bytes(table: pa.Table) -> bytes:
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, table.schema) as w:
        w.write_table(table)
    return sink.getvalue().to_pybytes()


def _session(app):
    return raydp_tpu.init(app, num_executors=2, executor_cores=1,
                          executor_memory="512MB")


def _frame(s, n=4000):
    rng = np.random.RandomState(0)
    pdf = pd.DataFrame({
        "k": rng.randint(0, 50, n),
        # integer aggregates only: bit-identical under any partial/merge
        # order (float partials may differ in the last ulp)
        "v": rng.randint(0, 1000, n).astype(np.int64),
    })
    return s.createDataFrame(pdf, num_partitions=4)


def _run_groupagg(app):
    """One full session running the canonical two-phase groupagg; returns
    (result ipc bytes, row count, engine shuffle-stage report). The table is
    canonicalized by sorting on the group key before serializing: pyarrow's
    hash aggregation is threaded, so groupagg ROW ORDER is unspecified even
    between two fault-free runs (like Spark's) — the byte-identity contract
    is over the relation, each value bit-exact."""
    s = _session(app)
    try:
        df = _frame(s)
        out = df.groupBy("k").agg(F.sum("v").alias("s"),
                                  F.count("v").alias("n"))
        n = s.engine.count(out._plan)
        table = s.engine.collect(out._plan).sort_by([("k", "ascending")])
        return _ipc_bytes(table), n, s.engine.shuffle_stage_report()
    finally:
        raydp_tpu.stop()


def test_executor_crash_mid_groupagg_byte_identical(tmp_path, monkeypatch):
    """An injected transient raise on the first task AND an executor crash on
    its 3rd task (the merge stage, after the 2 map tasks) — task retry with
    backoff must deliver the exact fault-free bytes."""
    base, base_n, _ = _run_groupagg("chaos-crash-base")

    raise_s = str(tmp_path / "raise.sentinel")
    crash_s = str(tmp_path / "crash.sentinel")
    monkeypatch.setenv(
        "RDT_FAULTS",
        f"executor.run_task:raise:nth=1:once={raise_s};"
        f"executor.run_task:crash:nth=3:once={crash_s}")
    got, got_n, _ = _run_groupagg("chaos-crash")
    assert os.path.exists(raise_s), "injected raise never fired"
    assert os.path.exists(crash_s), "injected crash never fired"
    assert got_n == base_n
    assert got == base


def test_dropped_shuffle_bucket_lineage_recovery(tmp_path, monkeypatch):
    """A shuffle bucket blob silently dropped after the map stage (the
    store-host-died model): the reduce stage hits ObjectLostError, the engine
    re-executes the producer from the lineage ledger, re-homes the blob,
    patches the consumer refs, and the action result is byte-identical. The
    stage report records the regenerated intermediate."""
    base, base_n, _ = _run_groupagg("chaos-drop-base")

    sent = str(tmp_path / "drop.sentinel")
    monkeypatch.setenv("RDT_FAULTS", f"shuffle.write:drop:nth=2:once={sent}")
    got, got_n, report = _run_groupagg("chaos-drop")
    assert os.path.exists(sent), "injected drop never fired"
    assert got_n == base_n
    assert got == base
    assert sum(e.get("regenerated", 0) for e in report) >= 1, report
    assert sum(e.get("recovered", 0) for e in report) >= 1, report


def test_dropped_consolidated_map_blob_recovery(tmp_path, monkeypatch):
    """Consolidated shuffle path (explicitly pinned on): a map task's output
    is ONE blob holding every bucket, so ``shuffle.write:drop`` must target
    that single consolidated oid — and one regenerated producer restores all
    B buckets at once. The reduce stage hits ObjectLostError on its byte
    range, lineage reruns the producer (byte-identical, so the bucket index
    still addresses the fresh blob), and the action result matches the
    fault-free run exactly with the recovery surfaced in the ledger."""
    monkeypatch.setenv("RDT_SHUFFLE_CONSOLIDATE", "1")
    base, base_n, base_report = _run_groupagg("chaos-consol-base")
    assert all(e["consolidated"] for e in base_report), base_report

    sent = str(tmp_path / "consol-drop.sentinel")
    # bucket=3 would pick bucket 3 of a legacy map output; the consolidated
    # map has exactly one blob, so the victim index wraps onto it
    monkeypatch.setenv("RDT_FAULTS",
                       f"shuffle.write:drop:nth=2:bucket=3:once={sent}")
    got, got_n, report = _run_groupagg("chaos-consol-drop")
    assert os.path.exists(sent), "injected drop never fired"
    assert got_n == base_n
    assert got == base
    entries = [e for e in report if e.get("recovered", 0) >= 1]
    assert entries, report
    # the regenerated producer is a consolidated map task: ONE blob rebuilt
    # brings back every bucket, so a single recovery event suffices
    assert all(e["consolidated"] for e in entries)
    assert sum(e.get("regenerated", 0) for e in report) >= 1, report


def test_straggler_speculation_composes_with_lineage_recovery(tmp_path,
                                                              monkeypatch):
    """A seeded one-executor straggler (every task entering executor 0 sleeps
    at entry) COMBINED with a dropped shuffle blob in the same action:
    speculative backup tasks and lineage recovery must compose — results
    byte-identical to the fault-free run, the drop recovered through the
    ledger, at least one backup fired, and the store object count back at
    its pre-action value (no orphans from won/lost speculation races; the
    losers land late and free through the late-result path, so the audit
    polls). The drop is pinned to nth=1: the fast executor's first map
    write, deterministically a WINNING attempt's blob — the delayed
    executor's first write trails it by the full injected delay."""
    from raydp_tpu.runtime.object_store import get_client

    base, _, _ = _run_groupagg("chaos-straggler-base")

    sent = str(tmp_path / "straggler-drop.sentinel")
    victim = "rdt-executor-chaos-straggler-0"
    monkeypatch.setenv(
        "RDT_FAULTS",
        f"executor.run_task:delay:ms=600:match={victim}|;"
        f"shuffle.write:drop:nth=1:once={sent}")
    monkeypatch.setenv("RDT_SPECULATION_QUANTILE", "0.25")
    monkeypatch.setenv("RDT_SPECULATION_MIN_S", "0.15")
    s = _session("chaos-straggler")
    try:
        client = get_client()
        df = _frame(s)
        before = client.stats()["num_objects"]
        out = df.groupBy("k").agg(F.sum("v").alias("s"),
                                  F.count("v").alias("n"))
        table = s.engine.collect(out._plan).sort_by([("k", "ascending")])
        report = s.engine.shuffle_stage_report()
        assert os.path.exists(sent), "injected drop never fired"
        assert _ipc_bytes(table) == base
        assert sum(e.get("recovered", 0) for e in report) >= 1, report
        assert sum(e.get("regenerated", 0) for e in report) >= 1, report
        assert sum(e.get("speculated", 0) for e in report) >= 1, report
        deadline = time.time() + 30
        while time.time() < deadline \
                and client.stats()["num_objects"] != before:
            time.sleep(0.25)
        after = client.stats()["num_objects"]
        assert after == before, (
            f"speculation races orphaned {after - before} store objects")
    finally:
        raydp_tpu.stop()


def test_dropped_bucket_without_recovery_raises_stage_error(tmp_path,
                                                            monkeypatch):
    """Same drop schedule with lineage recovery disabled: the action must
    fail with StageError — proving the injection bites and the green run
    above is the recovery's doing, not an accident of scheduling."""
    sent = str(tmp_path / "drop-off.sentinel")
    monkeypatch.setenv("RDT_FAULTS", f"shuffle.write:drop:nth=2:once={sent}")
    monkeypatch.setenv("RDT_LINEAGE_RECOVERY", "0")
    s = _session("chaos-drop-off")
    try:
        df = _frame(s)
        out = df.groupBy("k").agg(F.sum("v").alias("s"))
        with pytest.raises(StageError):
            s.engine.collect(out._plan)
        assert os.path.exists(sent), "injected drop never fired"
    finally:
        raydp_tpu.stop()


def test_cache_crash_then_lineage_rebuild(tmp_path, monkeypatch):
    """Executor crash during cache() materialization: the cache stage retries
    onto the surviving/restarted executor, and blocks the crashed executor
    already cached are rebuilt from their lineage recipes on read — collect
    equals the fault-free run exactly."""
    from raydp_tpu.etl.expressions import col

    def run(app):
        s = _session(app)
        try:
            cached = _frame(s).withColumn("v2", col("v") * 2).persist()
            assert cached.count() == 4000
            table = s.engine.collect(cached._plan)
            return _ipc_bytes(table)
        finally:
            raydp_tpu.stop()

    base = run("chaos-cache-clean")
    sent = str(tmp_path / "cache-crash.sentinel")
    monkeypatch.setenv("RDT_FAULTS",
                       f"executor.run_task:crash:nth=2:once={sent}")
    got = run("chaos-cache-crash")
    assert os.path.exists(sent), "injected crash never fired"
    assert got == base


def test_cache_recover_recipes_survive_bucket_drop(tmp_path, monkeypatch):
    """A shuffle bucket dropped while persist() materializes: the cache
    stage recovers in-flight, and — the regression this pins — the persisted
    frame's recovery RECIPES must reference the regenerated blob, not the
    dead id (recipes are serialized after the stage, patched). Proven by
    wiping every executor cache afterwards and reading the frame back
    through lineage."""
    import time

    base, base_n, _ = _run_groupagg("chaos-recipe-base")

    sent = str(tmp_path / "recipe-drop.sentinel")
    monkeypatch.setenv("RDT_FAULTS", f"shuffle.write:drop:nth=2:once={sent}")
    s = _session("chaos-recipe")
    try:
        df = _frame(s)
        cached = df.groupBy("k").agg(F.sum("v").alias("s"),
                                     F.count("v").alias("n")).persist()
        assert os.path.exists(sent), "injected drop never fired"
        assert sum(e.get("regenerated", 0)
                   for e in s.engine.shuffle_stage_report()) >= 1

        # wipe every cache (crash-restart); reads must rebuild via recipes
        for h in s.executors:
            try:
                h.call("crash")
            except Exception:
                pass
        deadline = time.time() + 60
        got_n = None
        while time.time() < deadline:
            try:
                got_n = s.engine.count(cached._plan)
                break
            except Exception:
                time.sleep(0.5)
        assert got_n == base_n
        table = s.engine.collect(cached._plan).sort_by([("k", "ascending")])
        assert _ipc_bytes(table) == base
    finally:
        raydp_tpu.stop()


def test_estimator_epoch_failure_checkpoint_resume(tmp_path):
    """Epoch 1 dies (injected at the estimator.epoch site); with
    max_retries=1 the fit restores the epoch-0 checkpoint, replays, and the
    final weights are bit-identical to an uninterrupted fit."""
    import optax

    from raydp_tpu.data.dataset import from_frame
    from raydp_tpu.models import MLP
    from raydp_tpu.train import FlaxEstimator

    s = _session("chaos-estimator")
    try:
        rng = np.random.RandomState(0)
        x = rng.random_sample((1024, 2))
        y = x @ np.array([2.0, -3.0]) + 1.0
        pdf = pd.DataFrame({"x1": x[:, 0], "x2": x[:, 1], "y": y})
        ds = from_frame(s.createDataFrame(pdf, num_partitions=4))

        def make(ckpt):
            return FlaxEstimator(
                model=MLP(features=(8,), use_batch_norm=False),
                optimizer=optax.adam(1e-2), loss="mse",
                feature_columns=["x1", "x2"], label_column="y",
                batch_size=128, num_epochs=3, seed=0,
                checkpoint_dir=str(tmp_path / ckpt))

        clean = make("clean").fit(ds)
        assert len(clean.history) == 3

        faults.clear()
        try:
            rule = faults.inject("estimator.epoch", "raise",
                                 match="1", times=1)
            est = make("faulted")
            faulted = est.fit(ds, max_retries=1)
        finally:
            faults.clear()
        assert rule.fires == 1, "epoch fault never fired"
        assert len(faulted.history) == 3

        import jax
        a = jax.tree_util.tree_leaves(clean.state.params)
        b = jax.tree_util.tree_leaves(faulted.state.params)
        assert len(a) == len(b) and len(a) > 0
        for la, lb in zip(a, b):
            np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))
    finally:
        raydp_tpu.stop()


def test_free_late_result_runs_off_callback_thread_unit():
    """The drain-abandonment callback fires on the executor connection's RPC
    read loop; its drop_blocks is a synchronous call over that SAME
    connection, so doing the work inline would block the only thread able to
    deliver the response — the callback must hand off and return at once."""
    import threading
    from concurrent.futures import Future

    from raydp_tpu.etl.engine import ExecutorPool

    release = threading.Event()
    dropped = threading.Event()

    class _Handle:
        def drop_blocks(self, keys, if_stamp=None):
            assert release.wait(5), "free thread never reached drop_blocks"
            assert keys == ["blk"]
            # the straggler's own generation stamp rides along, so the
            # executor only drops OUR stale entry, never a recovery
            # resubmit's fresh block cached under the same key
            assert if_stamp == "gen0"
            dropped.set()

    pool = ExecutorPool.__new__(ExecutorPool)
    pool.by_name = {"ex0": _Handle()}
    fut = Future()
    fut.set_result({"executor": "ex0", "cache_key": "blk",
                    "cache_stamp": "gen0"})

    t0 = time.monotonic()
    pool._free_late_result(fut)  # simulating the read-loop's callback call
    assert time.monotonic() - t0 < 1.0, \
        "callback blocked on the executor RPC instead of handing off"
    assert not dropped.is_set()
    release.set()
    assert dropped.wait(5), "handed-off free never ran"


def test_block_cache_stamp_conditioned_drop_unit():
    """A drain-abandoned CACHE straggler's deferred drop must not delete the
    live block a recovery resubmit cached under the same key: the drop is
    conditioned on the straggler's own generation stamp."""
    from raydp_tpu.etl.executor import BlockCache

    tbl = pa.table({"a": [1]})
    cache = BlockCache()
    cache.put("blk", tbl, stamp="old-gen")
    # the resubmit lands first, overwriting with a fresh generation
    cache.put("blk", tbl, stamp="new-gen")
    assert cache.drop(["blk"], if_stamp="old-gen") == 0
    assert cache.get("blk") is not None, "live resubmit block was dropped"
    # the straggler's drop DOES work when its generation is still current
    assert cache.drop(["blk"], if_stamp="new-gen") == 1
    assert cache.get("blk") is None
    # a lineage-rebuilt block (get_block re-put, no stamp) is also immune
    cache.put("blk", tbl)
    assert cache.drop(["blk"], if_stamp="old-gen") == 0
    # unconditional drops (persist sweeps) behave as before
    assert cache.drop(["blk"]) == 1


def test_patch_task_refs_surgery_unit():
    """Ref surgery (what recovery uses to point consumers at regenerated
    blobs) must reach every ref a task can hold — ArrowRefSource,
    HashJoinStep right side, a CachedSource's nested recovery task — and
    leave untouched tasks identity-equal. task_input_ids is the audit of the
    same traversal."""
    from raydp_tpu.etl import tasks as T
    from raydp_tpu.runtime.object_store import ObjectRef

    old = [ObjectRef(id=f"{i:032x}") for i in range(3)]
    new = ObjectRef(id="f" * 32)
    inner = T.Task(task_id="inner", source=T.ArrowRefSource([old[2]]))
    task = T.Task(
        task_id="outer",
        source=T.ArrowRefSource([old[0]]),
        steps=[T.HashJoinStep([old[1]], ["k"], ["k"]),
               T.CachedSource("key", recover=inner)])
    assert sorted(T.task_input_ids(task)) == sorted(r.id for r in old)

    patched = T.patch_task_refs(task, {old[0].id: new, old[2].id: new})
    ids = T.task_input_ids(patched)
    assert ids.count(new.id) == 2 and old[1].id in ids
    assert old[0].id not in ids and old[2].id not in ids
    # no-match mapping returns the identical object (no useless copies)
    assert T.patch_task_refs(task, {"e" * 32: new}) is task


def test_note_recovery_attribution_unit():
    """Recovery accounting must land on the entry of the stage that produced
    the lost blobs — not "the most recent entry with this label": concurrent
    actions interleave same-label entries in the engine deque, and one action
    can run the same label twice (two joins, two groupbys)."""
    import collections
    import threading

    from raydp_tpu.etl.engine import Engine, _ActionTemps, _Producer

    eng = Engine.__new__(Engine)
    eng._report_lock = threading.Lock()
    eng._stage_reports = collections.deque(maxlen=256)
    eng.tenant = "unit"

    def record(temps, label, ref_id):
        prod = _Producer(b"", [ref_id], label)
        temps.lineage[ref_id] = prod
        eng._record_stage(label, [{"num_rows": 1, "ref": ObjectRef(id=ref_id)}],
                          2, temps)
        return prod

    temps_a, temps_b = _ActionTemps(), _ActionTemps()
    prod_a = record(temps_a, "groupagg", "a" * 32)
    record(temps_b, "groupagg", "b" * 32)  # concurrent action, newer entry
    prod_a2 = record(temps_a, "groupagg", "c" * 32)  # same label, 2nd stage

    eng._note_recovery(prod_a, 3, temps_a)  # A's FIRST stage recovers
    report = eng.shuffle_stage_report()
    assert [e["regenerated"] for e in report] == [3, 0, 0], report
    assert [e["recovered"] for e in report] == [1, 0, 0], report
    eng._note_recovery(prod_a2, 2, temps_a)  # A's second stage, own entry
    assert [e["regenerated"] for e in eng.shuffle_stage_report()] == [3, 0, 2]

    # a label the action never recorded gets its own bare entry, and a
    # second recovery of the same label accumulates there (no duplicates)
    mat = _Producer(b"", ["d" * 32], "materialize")
    eng._note_recovery(mat, 1, temps_a)
    eng._note_recovery(mat, 2, temps_a)
    mats = [e for e in eng.shuffle_stage_report()
            if e["stage"] == "materialize"]
    assert len(mats) == 1
    assert mats[0]["regenerated"] == 3 and mats[0]["recovered"] == 2


def test_ref_patches_transitive_collapse_unit():
    """A second-generation loss (A regenerated as B, then B lost and
    regenerated as C) must leave ref_patches mapping A → C: cache() recover
    recipes are serialized through this map, and a recipe pointing at the
    freed intermediate B would be permanently unrecoverable (a later action
    has no lineage for B)."""
    from raydp_tpu.etl.engine import _ActionTemps

    a, b, c = ("a" * 32, "b" * 32, "c" * 32)
    temps = _ActionTemps()
    temps.apply_patches({a: ObjectRef(id=b)})
    temps.apply_patches({b: ObjectRef(id=c)})
    assert temps.ref_patches[a].id == c
    assert temps.ref_patches[b].id == c


def test_expand_lost_dead_host_unit(monkeypatch):
    """The multi-loss probe must share the read path's loss criterion: a
    reported-lost blob the store table still lists means its payload host is
    unreachable (purge_host lags a node death), so every ledgered candidate
    homed there is equally lost — while a head-local loss stays blob-specific
    and blobs on live hosts are left alone."""
    from raydp_tpu.etl import engine as E
    from raydp_tpu.etl import tasks as T

    # candidate inputs of the one unfinished task; L* are the reported losses
    c_dead, c_live, c_freed, c_head = ("c1" * 16, "c2" * 16, "c3" * 16,
                                       "c4" * 16)
    l_node, l_head = "f1" * 16, "f2" * 16
    locs = {l_node: "node-a", l_head: "head",  # table still lists both
            c_dead: "node-a", c_live: "node-b", c_head: "head"}
    # c_freed absent: freed/purged — lost via the plain presence check

    class _StubClient:
        def locations(self, refs):
            return {r.id: locs[r.id] for r in refs if r.id in locs}

    monkeypatch.setattr(E, "get_client", lambda: _StubClient())

    temps = E._ActionTemps()
    for cid in (c_dead, c_live, c_freed, c_head):
        temps.lineage[cid] = E._Producer(b"", [cid], "groupagg")
    task = T.Task(task_id="t0", source=T.ArrowRefSource(
        [ObjectRef(id=i) for i in (c_dead, c_live, c_freed, c_head)]))

    lost = E.Engine._expand_lost([l_node, l_head], [task], [None], temps)
    # node-a listed a blob whose read failed => node-a is dead => c_dead
    # joins; c_freed is absent from the table; head and node-b stay put
    assert lost == {l_node, l_head, c_dead, c_freed}


def test_failed_action_leaves_no_orphaned_store_objects():
    """Regression for the temps/abort lifecycle: an action that dies mid-map
    stage (a deterministic app error in ONE partition while the siblings'
    shuffle buckets are already written) must drain in-flight tasks and free
    every intermediate — the store object count returns to its pre-action
    value."""
    from raydp_tpu.etl.expressions import udf
    from raydp_tpu.runtime.object_store import get_client

    s = _session("chaos-orphans")
    try:
        rng = np.random.RandomState(1)
        vals = rng.randint(0, 100, 4000)
        vals[3600] = 777  # the poison pill lives in the LAST partition only
        pdf = pd.DataFrame({"k": rng.randint(0, 10, 4000), "v": vals})
        df = s.createDataFrame(pdf, num_partitions=4)

        client = get_client()
        before = client.stats()["num_objects"]

        @udf("int")
        def poison(v):
            if v == 777:
                raise ValueError("poison pill")
            return int(v)

        out = df.withColumn("p", poison("v")).groupBy("k").agg(
            F.sum("p").alias("s"))
        with pytest.raises(StageError):
            s.engine.collect(out._plan)

        after = client.stats()["num_objects"]
        assert after == before, (
            f"failed action leaked {after - before} store objects")
    finally:
        raydp_tpu.stop()


def test_failed_persist_leaves_no_cached_blocks():
    """Regression for the executor-RAM half of the abort contract: when
    persist() dies on one partition, the sibling partitions have already
    stored their tables in executor block caches — beyond the store-count
    audit above. The abort must sweep those blocks from every executor, or
    each retried persist of a failing plan pins more partition tables in the
    unbounded BlockCache."""
    from raydp_tpu.etl.expressions import udf
    from raydp_tpu.runtime.object_store import get_client

    s = _session("chaos-persist-abort")
    try:
        rng = np.random.RandomState(3)
        vals = rng.randint(0, 100, 4000)
        vals[3600] = 777  # poison only the LAST partition
        pdf = pd.DataFrame({"k": rng.randint(0, 10, 4000), "v": vals})
        df = s.createDataFrame(pdf, num_partitions=4)

        client = get_client()
        before = client.stats()["num_objects"]
        blocks_before = {h.name: set(h.list_blocks()) for h in s.executors}

        @udf("int")
        def poison(v):
            if v == 777:
                raise ValueError("poison pill")
            return int(v)

        with pytest.raises(StageError):
            df.withColumn("p", poison("v")).persist()

        assert client.stats()["num_objects"] == before
        for h in s.executors:
            assert set(h.list_blocks()) == blocks_before[h.name], (
                f"aborted persist left cached blocks on {h.name}")
    finally:
        raydp_tpu.stop()


def test_shuffle_write_raise_after_put_leaves_no_orphans(tmp_path,
                                                        monkeypatch):
    """An injected raise at shuffle.write fires AFTER the task's bucket blobs
    hit the store; the retry writes fresh copies, so the executor must free
    the first set — the action succeeds and the store count returns to its
    pre-action value (plus nothing: collect holds no refs at the end)."""
    from raydp_tpu.runtime.object_store import get_client

    sent = str(tmp_path / "wraise.sentinel")
    monkeypatch.setenv("RDT_FAULTS", f"shuffle.write:raise:nth=1:once={sent}")
    s = _session("chaos-wraise")
    try:
        df = _frame(s)
        client = get_client()
        before = client.stats()["num_objects"]
        out = df.groupBy("k").agg(F.sum("v").alias("s"))
        table = s.engine.collect(out._plan)
        assert table.num_rows > 0
        assert os.path.exists(sent), "injected shuffle.write raise never fired"
        after = client.stats()["num_objects"]
        assert after == before, (
            f"retried shuffle write leaked {after - before} store objects")
    finally:
        raydp_tpu.stop()


# ==== pipelined shuffle under chaos (ISSUE 8) ======================================
def _run_groupagg_pipelined(app, pipeline="1"):
    """The canonical groupagg with AQE pinned off so the pipelined mode
    actually engages (the AQE-wins rule barriers AQE-capable stages);
    ``pipeline="0"`` is the fault-free BARRIER baseline the pipelined chaos
    legs compare byte-identical against."""
    os.environ["RDT_ETL_AQE"] = "0"
    os.environ["RDT_SHUFFLE_PIPELINE"] = pipeline
    try:
        return _run_groupagg(app)
    finally:
        os.environ.pop("RDT_ETL_AQE", None)
        os.environ.pop("RDT_SHUFFLE_PIPELINE", None)


def test_pipelined_stale_range_regenerates_and_reseals(tmp_path,
                                                       monkeypatch):
    """Chaos leg (a): a map blob dropped AFTER its seal notification but
    BEFORE the reducer's fetch — ``shuffle.write:drop`` frees the
    consolidated blob executor-side, yet the winning result still reaches
    the driver, which publishes the seal; the streaming reducer's fetch of
    the now-stale range hits ObjectLostError, rides the existing lineage
    path (regenerate producer → RE-SEAL under the same map_id, next
    generation → resubmit), and the result is byte-identical to a
    fault-free BARRIER run."""
    base, base_n, _ = _run_groupagg_pipelined("chaos-pipe-base",
                                              pipeline="0")

    sent = str(tmp_path / "pipe-drop.sentinel")
    monkeypatch.setenv("RDT_FAULTS", f"shuffle.write:drop:nth=2:once={sent}")
    got, got_n, report = _run_groupagg_pipelined("chaos-pipe-drop")
    assert os.path.exists(sent), "injected drop never fired"
    assert got_n == base_n
    assert got == base
    assert any(e["pipelined"] for e in report), report
    assert sum(e.get("recovered", 0) for e in report) >= 1, report
    assert sum(e.get("regenerated", 0) for e in report) >= 1, report


def test_pipelined_speculation_losers_never_seal(tmp_path, monkeypatch):
    """Chaos leg (b): speculation loser seals racing the winner. A seeded
    one-executor straggler forces backup map tasks; only the FIRST
    finisher's result reaches the driver, so only the winner's blob is ever
    published to the seal stream — no duplicate bucket rows — and the
    losers' blobs free through the late-result path (store count back to
    the pre-action baseline)."""
    from raydp_tpu.runtime.object_store import get_client

    base, _, _ = _run_groupagg_pipelined("chaos-pipe-spec-base",
                                         pipeline="0")

    app = "chaos-pipe-spec"
    victim = f"rdt-executor-{app}-0"
    monkeypatch.setenv("RDT_FAULTS",
                       f"executor.run_task:delay:ms=600:match={victim}|")
    monkeypatch.setenv("RDT_SPECULATION_QUANTILE", "0.25")
    monkeypatch.setenv("RDT_SPECULATION_MIN_S", "0.15")
    monkeypatch.setenv("RDT_ETL_AQE", "0")
    monkeypatch.setenv("RDT_SHUFFLE_PIPELINE", "1")
    s = _session(app)
    try:
        client = get_client()
        df = _frame(s)
        before = client.stats()["num_objects"]
        out = df.groupBy("k").agg(F.sum("v").alias("s"),
                                  F.count("v").alias("n"))
        table = s.engine.collect(out._plan).sort_by([("k", "ascending")])
        report = s.engine.shuffle_stage_report()
        assert _ipc_bytes(table) == base, \
            "a loser's seal leaked duplicate bucket rows"
        assert any(e["pipelined"] for e in report), report
        assert sum(e.get("speculated", 0) for e in report) >= 1, report
        deadline = time.time() + 30
        while time.time() < deadline \
                and client.stats()["num_objects"] != before:
            time.sleep(0.2)
        after = client.stats()["num_objects"]
        assert after == before, (
            f"pipelined speculation races orphaned {after - before} blobs")
    finally:
        raydp_tpu.stop()


def test_pipelined_streamed_fetch_drop_recovery(tmp_path, monkeypatch):
    """Chaos leg (c): pipelining + ``shuffle.fetch:drop`` — the drop fires
    INSIDE a streaming reducer's fetch round (frees the backing blob, then
    the typed loss), mid-stream with other portions already decoded; the
    regenerated producer re-seals and the resubmitted reducer re-reads the
    whole bucket byte-identical to a fault-free barrier run."""
    base, base_n, _ = _run_groupagg_pipelined("chaos-pipe-fdrop-base",
                                              pipeline="0")

    sent = str(tmp_path / "pipe-fdrop.sentinel")
    monkeypatch.setenv("RDT_FAULTS",
                       f"shuffle.fetch:drop:nth=2:once={sent}")
    got, got_n, report = _run_groupagg_pipelined("chaos-pipe-fdrop")
    assert os.path.exists(sent), "injected streamed-fetch drop never fired"
    assert got_n == base_n
    assert got == base
    assert any(e["pipelined"] for e in report), report
    assert sum(e.get("recovered", 0) for e in report) >= 1, report


# ==== adaptive execution under chaos (ISSUE 7) =====================================
def _run_broadcast_join(app):
    """One session running the canonical broadcast join (small dim side →
    AQE replicates it, neither side shuffles); returns (result ipc bytes,
    row count, report)."""
    s = _session(app)
    try:
        rng = np.random.RandomState(2)
        n = 4000
        big = s.createDataFrame(
            pd.DataFrame({"k": rng.randint(0, 30, n),
                          "v": rng.randint(0, 1000, n).astype(np.int64)}),
            num_partitions=4)
        dim = s.createDataFrame(
            pd.DataFrame({"k": np.arange(30),
                          "lab": (np.arange(30) * 3).astype(np.int64)}),
            num_partitions=2)
        out = big.join(dim, on="k").select("k", "v", "lab")
        n_rows = s.engine.count(out._plan)
        table = s.engine.collect(out._plan).sort_by(
            [("k", "ascending"), ("v", "ascending")])
        return _ipc_bytes(table), n_rows, s.engine.shuffle_stage_report()
    finally:
        raydp_tpu.stop()


def test_dropped_broadcast_replica_blob_recovery(tmp_path, monkeypatch):
    """A broadcast side's store blob silently dropped before any executor
    fetched its replica (``shuffle.fetch:drop`` — the first RANGED read in
    an executor is a broadcast fetch, since a pre-shuffle broadcast join has
    no other ranged reads): the probe task hits ObjectLostError, lineage
    regenerates the small side's producer (ledgered under join-broadcast),
    the BroadcastJoinStep's parts are patched to the fresh blob (a new
    broadcast-cache key, so no executor probes stale bytes), and the join
    result is byte-identical. The report shows both the broadcast AND the
    recovery."""
    base, base_n, base_rep = _run_broadcast_join("chaos-bcast-base")
    assert sum(e.get("aqe_broadcast", 0) for e in base_rep) >= 1, base_rep

    sent = str(tmp_path / "bcast-drop.sentinel")
    monkeypatch.setenv("RDT_FAULTS", f"shuffle.fetch:drop:nth=1:once={sent}")
    got, got_n, report = _run_broadcast_join("chaos-bcast-drop")
    assert os.path.exists(sent), "injected broadcast-replica drop never fired"
    assert got_n == base_n
    assert got == base
    assert sum(e.get("aqe_broadcast", 0) for e in report) >= 1, report
    assert sum(e.get("recovered", 0) for e in report) >= 1, report
    assert sum(e.get("regenerated", 0) for e in report) >= 1, report


def _run_skew_groupagg(app):
    """One session running a skew-split groupby (hot key ~50%, unique-first
    chunks so row-wise partials carry the skew to the reduce side)."""
    s = _session(app)
    try:
        rng = np.random.RandomState(9)
        rows, parts = 16_000, 4
        per = rows // parts
        chunks, nxt = [], 1
        for _ in range(parts):
            nu = per // 2
            ks = np.concatenate([np.arange(nxt, nxt + nu) * 7 + 3,
                                 np.zeros(per - nu, dtype=np.int64)])
            nxt += nu
            chunks.append(pd.DataFrame(
                {"k": ks, "v": rng.randint(0, 1000, per).astype(np.int64)}))
        df = s.createDataFrame(pd.concat(chunks).reset_index(drop=True),
                               num_partitions=parts)
        out = df.groupBy("k").agg(F.sum("v").alias("sv"),
                                  F.count("v").alias("n"))
        table = s.engine.collect(out._plan).sort_by([("k", "ascending")])
        return _ipc_bytes(table), s.engine.shuffle_stage_report()
    finally:
        raydp_tpu.stop()


def test_dropped_split_read_source_mid_skew_recovery(tmp_path, monkeypatch):
    """A map blob dropped exactly when a SPLIT task's ranged read touches it
    (``shuffle.fetch:drop:nth=1`` — the split stage issues the first ranged
    reads of the action): lineage regenerates the map producer, the split
    task's RangeRefSource is patched (offsets survive: reruns are
    byte-identical), and the re-planned aggregation is byte-identical with
    both the split and the recovery in the ledger."""
    monkeypatch.setenv("RDT_AQE_COALESCE_MIN", "0")
    monkeypatch.setenv("RDT_AQE_SKEW_FACTOR", "2")
    base, base_rep = _run_skew_groupagg("chaos-skew-base")
    assert sum(e.get("aqe_split", 0) for e in base_rep) >= 1, base_rep

    sent = str(tmp_path / "split-drop.sentinel")
    monkeypatch.setenv("RDT_FAULTS", f"shuffle.fetch:drop:nth=1:once={sent}")
    got, report = _run_skew_groupagg("chaos-skew-drop")
    assert os.path.exists(sent), "injected split-read drop never fired"
    assert got == base
    assert sum(e.get("aqe_split", 0) for e in report) >= 1, report
    assert sum(e.get("recovered", 0) for e in report) >= 1, report


def test_broadcast_speculation_losers_leave_no_orphans(tmp_path,
                                                       monkeypatch):
    """The no-orphan store-count contract with BROADCAST replicas in the
    race: a seeded one-executor straggler makes the broadcast side's
    materialize tasks speculate; the losing copy's blob is a duplicate
    broadcast replica that reaches no caller and must free through the
    loser-drain path — after the action settles, the store count returns to
    its pre-action baseline and the result matches a straggler-free run."""
    from raydp_tpu.runtime.object_store import get_client

    base, base_n, _ = _run_broadcast_join("chaos-bcast-spec-base")

    app = "chaos-bcast-spec"
    victim = f"rdt-executor-{app}-0"
    monkeypatch.setenv("RDT_FAULTS",
                       f"executor.run_task:delay:ms=600:match={victim}|")
    monkeypatch.setenv("RDT_SPECULATION", "1")
    monkeypatch.setenv("RDT_SPECULATION_QUANTILE", "0.5")
    monkeypatch.setenv("RDT_SPECULATION_MIN_S", "0.2")
    s = _session(app)
    try:
        rng = np.random.RandomState(2)
        n = 4000
        big = s.createDataFrame(
            pd.DataFrame({"k": rng.randint(0, 30, n),
                          "v": rng.randint(0, 1000, n).astype(np.int64)}),
            num_partitions=4)
        dim = s.createDataFrame(
            pd.DataFrame({"k": np.arange(30),
                          "lab": (np.arange(30) * 3).astype(np.int64)}),
            num_partitions=2)
        client = get_client()
        before = client.stats()["num_objects"]
        out = big.join(dim, on="k").select("k", "v", "lab")
        n_rows = s.engine.count(out._plan)
        table = s.engine.collect(out._plan).sort_by(
            [("k", "ascending"), ("v", "ascending")])
        report = s.engine.shuffle_stage_report()
        assert n_rows == base_n
        assert _ipc_bytes(table) == base
        assert sum(e.get("aqe_broadcast", 0) for e in report) >= 1, report
        # losing duplicates land late and free through the loser path:
        # poll the store audit back to the pre-action baseline
        deadline = time.time() + 30
        while time.time() < deadline \
                and client.stats()["num_objects"] != before:
            time.sleep(0.2)
        orphans = client.stats()["num_objects"] - before
        assert orphans == 0, (
            f"broadcast speculation races orphaned {orphans} store objects")
    finally:
        raydp_tpu.stop()


# ==== elastic pool under chaos (ISSUE 13) ==========================================
def _session3(app):
    return raydp_tpu.init(app, num_executors=3, executor_cores=1,
                          executor_memory="512MB")


def _collect_groupagg_during_retire(app, victim_suffix="-2",
                                    retire_after_s=0.4):
    """Start the canonical groupagg on a background thread, retire one
    executor mid-action, join, and return (ipc bytes, report, session-level
    facts). The session is fully torn down before returning."""
    from raydp_tpu.runtime.object_store import get_client

    s = _session3(app)
    try:
        df = _frame(s)
        client = get_client()
        before = client.stats()["num_objects"]
        out = df.groupBy("k").agg(F.sum("v").alias("s"),
                                  F.count("v").alias("n"))
        box = {}

        def run():
            try:
                box["table"] = s.engine.collect(out._plan) \
                    .sort_by([("k", "ascending")])
            except Exception as e:  # noqa: BLE001 - asserted below
                box["error"] = e

        t = threading.Thread(target=run)
        t.start()
        time.sleep(retire_after_s)
        victim = f"rdt-executor-{app}{victim_suffix}"
        s.retire_executor(victim)
        t.join(timeout=300)
        assert not t.is_alive(), "action wedged across the retirement"
        if "error" in box:
            raise box["error"]
        # store-count audit: the drain + recovery leave zero orphans
        # (late losers/regenerations free asynchronously: poll)
        deadline = time.time() + 30
        while time.time() < deadline \
                and client.stats()["num_objects"] != before:
            time.sleep(0.25)
        orphans = client.stats()["num_objects"] - before
        return (_ipc_bytes(box["table"]), s.engine.shuffle_stage_report(),
                {"orphans": orphans, "pool": len(s.executors),
                 "survivors": [h.name for h in s.executors]})
    finally:
        raydp_tpu.stop()


def test_scale_down_races_lineage_recovery(tmp_path, monkeypatch):
    """Chaos leg (ISSUE 13a): a graceful scale-down races an in-flight
    lineage recovery round. A dropped map blob forces recovery while every
    task is slowed enough that the retirement lands mid-action: the drain
    takes the retiring executor out of rotation, its in-flight tasks finish
    or re-queue, and the recovery round re-runs producers on the shrunken
    pool — byte-identical to a fault-free FIXED-pool run, zero orphaned
    store objects, recovery surfaced in the ledger."""
    s = _session3("chaos-scaledown-base")
    try:
        df = _frame(s)
        out = df.groupBy("k").agg(F.sum("v").alias("s"),
                                  F.count("v").alias("n"))
        base = _ipc_bytes(s.engine.collect(out._plan)
                          .sort_by([("k", "ascending")]))
    finally:
        raydp_tpu.stop()

    sent = str(tmp_path / "scaledown-drop.sentinel")
    monkeypatch.setenv(
        "RDT_FAULTS",
        "executor.run_task:delay:ms=250;"
        f"shuffle.write:drop:nth=2:once={sent}")
    got, report, facts = _collect_groupagg_during_retire("chaos-scaledown")
    assert os.path.exists(sent), "injected drop never fired"
    assert got == base
    assert facts["pool"] == 2, facts
    assert facts["orphans"] == 0, (
        f"scale-down racing recovery orphaned {facts['orphans']} objects")
    assert sum(e.get("recovered", 0) for e in report) >= 1, report
    assert sum(e.get("regenerated", 0) for e in report) >= 1, report


def test_scale_down_drain_crash_races_pipelined_stream(tmp_path,
                                                      monkeypatch):
    """Chaos leg (ISSUE 13b): the retiring executor DIES mid-drain
    (``pool.drain:crash``) while a pipelined shuffle it feeds is
    mid-stream. Its unfinished map tasks fail and re-run on survivors,
    their seals publish (or re-seal under the next generation through the
    PR 7 machinery), streaming reducers keep decoding — byte-identical to
    a fault-free fixed-pool BARRIER run, zero orphans."""
    monkeypatch.setenv("RDT_ETL_AQE", "0")
    monkeypatch.setenv("RDT_SHUFFLE_PIPELINE", "0")
    s = _session3("chaos-draincrash-base")
    try:
        df = _frame(s)
        out = df.groupBy("k").agg(F.sum("v").alias("s"),
                                  F.count("v").alias("n"))
        base = _ipc_bytes(s.engine.collect(out._plan)
                          .sort_by([("k", "ascending")]))
    finally:
        raydp_tpu.stop()

    sent = str(tmp_path / "drain-crash.sentinel")
    monkeypatch.setenv("RDT_SHUFFLE_PIPELINE", "1")
    monkeypatch.setenv(
        "RDT_FAULTS",
        "executor.run_task:delay:ms=250:match=|mt-;"
        f"pool.drain:crash:once={sent}")
    got, report, facts = _collect_groupagg_during_retire(
        "chaos-draincrash", retire_after_s=0.3)
    assert os.path.exists(sent), "drain-crash schedule never fired"
    assert got == base
    assert any(e["pipelined"] for e in report), report
    assert facts["pool"] == 2, facts
    assert facts["orphans"] == 0, (
        f"drain-crash mid-stream orphaned {facts['orphans']} objects")


def test_scale_down_races_live_serving_replica(tmp_path):
    """Chaos leg (ISSUE 13c): the executor hosting a live serving replica
    is retired mid-burst. In-flight dispatches re-route through the hedge
    path, the background reload routes through the pool's LIVE-member view
    and re-homes the replica onto a survivor (satellite fix — it used to
    probe the retired corpse until the grace expired) — zero dropped
    requests, results byte-identical to a fault-free fixed-pool run."""
    import optax

    from raydp_tpu.models import MLP
    from raydp_tpu.serve import ServingSession
    from raydp_tpu.train import FlaxEstimator

    rng = np.random.RandomState(11)
    x = rng.random_sample((512, 2))
    y = x @ np.array([2.0, -3.0]) + 1.0
    pdf = pd.DataFrame({"x1": x[:, 0], "x2": x[:, 1], "y": y})
    export_dir = str(tmp_path / "scale-servable")
    results, reports = {}, {}

    for mode in ("clean", "retire"):
        os.environ["RDT_SERVE_BATCH_TIMEOUT_MS"] = "10"
        s = raydp_tpu.init(f"serve_scale_{mode}", num_executors=3,
                           executor_cores=1, executor_memory="512MB")
        try:
            if mode == "clean":
                df = s.createDataFrame(pdf, num_partitions=2)
                est = FlaxEstimator(
                    model=MLP(features=(8,), use_batch_norm=False),
                    optimizer=optax.adam(1e-2), loss="mse",
                    feature_columns=["x1", "x2"], label_column="y",
                    batch_size=64, num_epochs=1)
                est.fit_on_frame(df)
                est.export_serving(export_dir)
            srv = ServingSession(export_dir, session=s, name="scalesrv")
            try:
                futs = [srv.predict_async({"x1": x[i:i + 2, 0],
                                           "x2": x[i:i + 2, 1]})
                        for i in range(0, 64, 2)]
                if mode == "retire":
                    # replica scalesrv-r0 lives on executor 0: retire it
                    # with the burst in flight
                    s.retire_executor(f"rdt-executor-serve_scale_{mode}-0")
                burst = [f.result(timeout=120.0) for f in futs]
                tail = [srv.predict({"x1": x[64 + i:65 + i, 0],
                                     "x2": x[64 + i:65 + i, 1]},
                                    timeout=120.0)
                        for i in range(16)]
                results[mode] = np.concatenate(burst + tail)
                # the re-homed replica's background reload may still be
                # jitting on the survivor: poll until it is back in rotation
                deadline = time.time() + 60
                while True:
                    reports[mode] = srv.serving_report()
                    if all(r["ready"] for r in reports[mode]["replicas"]) \
                            or time.time() > deadline:
                        break
                    time.sleep(0.25)
            finally:
                srv.close()
        finally:
            raydp_tpu.stop()
            os.environ.pop("RDT_SERVE_BATCH_TIMEOUT_MS", None)

    assert reports["retire"]["failed"] == 0, reports["retire"]
    assert len(results["retire"]) == len(results["clean"]) == 80
    assert np.array_equal(results["clean"], results["retire"])
    # the replica re-homed off the retired executor onto a survivor
    r0 = next(r for r in reports["retire"]["replicas"]
              if r["replica"] == "scalesrv-r0")
    assert r0["executor"] != "rdt-executor-serve_scale_retire-0", r0
    assert r0["ready"], r0


def test_serving_replica_crash_reroutes_zero_dropped(tmp_path):
    """ISSUE 11 serving chaos leg: a replica crash mid-stream under seeded
    load re-routes the in-flight (and every later) request through the
    hedge path — ZERO dropped requests, results byte-identical to a
    fault-free run. The crashed executor restarts (max_restarts=-1) and the
    replica reloads in the background; the once= sentinel keeps the
    restarted process from re-crashing on the inherited spec."""
    import optax

    from raydp_tpu.models import MLP
    from raydp_tpu.serve import ServingSession
    from raydp_tpu.train import FlaxEstimator

    rng = np.random.RandomState(11)
    x = rng.random_sample((512, 2))
    y = x @ np.array([2.0, -3.0]) + 1.0
    pdf = pd.DataFrame({"x1": x[:, 0], "x2": x[:, 1], "y": y})
    export_dir = str(tmp_path / "chaos-servable")
    sentinel = str(tmp_path / "serve_crash.sentinel")
    results, reports = {}, {}

    for mode in ("clean", "crash"):
        if mode == "crash":
            # the 2nd batch entering replica chaos-r0's worker kills its
            # executor process abruptly, mid-request (env set BEFORE init so
            # the spawned executors inherit it)
            os.environ["RDT_FAULTS"] = (
                f"serve.predict:crash:nth=2:match=|chaos-r0:once={sentinel}")
        os.environ["RDT_SERVE_BATCH_TIMEOUT_MS"] = "10"
        s = _session(f"serve_chaos_{mode}")
        try:
            if mode == "clean":
                df = s.createDataFrame(pdf, num_partitions=2)
                est = FlaxEstimator(
                    model=MLP(features=(8,), use_batch_norm=False),
                    optimizer=optax.adam(1e-2), loss="mse",
                    feature_columns=["x1", "x2"], label_column="y",
                    batch_size=64, num_epochs=1)
                est.fit_on_frame(df)
                est.export_serving(export_dir)
            srv = ServingSession(export_dir, session=s, name="chaos")
            try:
                # seeded load: a concurrent burst (coalesces, and is what
                # the crash lands in the middle of) + a sequential tail
                # (proves the plane keeps serving after the loss)
                futs = [srv.predict_async({"x1": x[i:i + 2, 0],
                                           "x2": x[i:i + 2, 1]})
                        for i in range(0, 64, 2)]
                burst = [f.result(timeout=120.0) for f in futs]
                tail = [srv.predict({"x1": x[64 + i:65 + i, 0],
                                     "x2": x[64 + i:65 + i, 1]},
                                    timeout=120.0)
                        for i in range(16)]
                results[mode] = np.concatenate(burst + tail)
                reports[mode] = srv.serving_report()
            finally:
                srv.close()
        finally:
            raydp_tpu.stop()
            os.environ.pop("RDT_FAULTS", None)
            os.environ.pop("RDT_SERVE_BATCH_TIMEOUT_MS", None)

    # the injection actually fired, and every request still completed
    assert os.path.exists(sentinel), "crash schedule never fired"
    assert reports["crash"]["failed"] == 0
    assert reports["crash"]["rerouted"] >= 1, reports["crash"]
    assert len(results["crash"]) == len(results["clean"]) == 80
    # byte-identical to the fault-free run (row-independent jitted apply:
    # neither the crash nor the changed batch composition may leak into
    # the numbers)
    assert np.array_equal(results["clean"], results["crash"])


# ==== guarded rollouts under chaos (ISSUE 18) ================================

def _guard_traffic(srv, x, n, out, timeout=120.0):
    """Sequential seeded load for the rollout legs: ``n`` 2-row predicts in
    a FIXED order, responses appended in that order — two runs (with and
    without a rollout in flight) produce position-comparable sequences."""
    for i in range(n):
        j = (2 * i) % 400
        out.append(srv.predict({"x1": x[j:j + 2, 0],
                                "x2": x[j:j + 2, 1]}, timeout=timeout))


def test_rollout_canary_latency_regression_rolls_back(tmp_path):
    """ISSUE 18 chaos leg (a): a canary whose every predict is stalled by a
    seeded ``serve.predict:delay`` (replica-id match ``-v2-`` pins the
    injection to the canary group alone) is judged unhealthy on the p99 arm
    and AUTO-ROLLS-BACK mid-traffic: zero dropped requests, results
    byte-identical to a rollout-free run, and the postmortem artifacts — a
    ``rollout_rollback`` event plus a flight-recorder blackbox bundle — are
    present. The delay rule has no once= sentinel (it must fire on every
    canary call to regress the p99 window); the ``"p99"`` rollback reason is
    the proof the injection bit."""
    import optax

    from raydp_tpu import metrics
    from raydp_tpu.models import MLP
    from raydp_tpu.runtime import head as head_mod
    from raydp_tpu.serve import ServingSession
    from raydp_tpu.train import FlaxEstimator

    rng = np.random.RandomState(11)
    x = rng.random_sample((512, 2))
    y = x @ np.array([2.0, -3.0]) + 1.0
    pdf = pd.DataFrame({"x1": x[:, 0], "x2": x[:, 1], "y": y})
    dir_v1 = str(tmp_path / "guard-v1")
    dir_v2 = str(tmp_path / "guard-v2")
    results, reports = {}, {}
    outcome = None

    for mode in ("clean", "rollout"):
        if mode == "rollout":
            # EVERY canary predict (replica ids guard-v2-r*) stalls 700ms —
            # a pure latency regression (no errors): only the p99 arm can
            # catch it (env set BEFORE init so executors inherit it). The
            # stall dwarfs any host-noise inflation of the baseline p99: a
            # loaded suite run must still clear the 2x judgment bar, or the
            # verdict would flap healthy and ramp a genuinely slow canary.
            os.environ["RDT_FAULTS"] = \
                "serve.predict:delay:ms=700:match=-v2-"
        os.environ["RDT_SERVE_BATCH_TIMEOUT_MS"] = "10"
        os.environ["RDT_SERVE_HEDGE"] = "0"
        s = _session(f"serve_rollout_{mode}")
        try:
            if mode == "clean":
                df = s.createDataFrame(pdf, num_partitions=2)
                est = FlaxEstimator(
                    model=MLP(features=(8,), use_batch_norm=False),
                    optimizer=optax.adam(1e-2), loss="mse",
                    feature_columns=["x1", "x2"], label_column="y",
                    batch_size=64, num_epochs=1)
                est.fit_on_frame(df)
                est.export_serving(dir_v1)
                # the canary is the SAME weights exported again: responses
                # must be byte-identical whichever version answers, so the
                # identity assert covers requests served mid-ramp too
                est.export_serving(dir_v2)
            srv = ServingSession(dir_v1, session=s, name="guard")
            try:
                got = []
                t = threading.Thread(target=_guard_traffic,
                                     args=(srv, x, 120, got))
                t.start()
                try:
                    if mode == "rollout":
                        outcome = srv.rollout(
                            dir_v2, tag="regressed", initial_weight=0.5,
                            steps=[0.5, 1.0], step_s=20.0, min_samples=6,
                            p99_factor=2.0, timeout=120.0)
                finally:
                    t.join(timeout=180.0)
                assert not t.is_alive(), "traffic thread hung"
                results[mode] = np.concatenate(got)
                reports[mode] = srv.serving_report()
                if mode == "rollout":
                    # postmortem artifacts, checked while the session (and
                    # its session_dir) is live
                    kinds = [e["kind"] for e in metrics.events()]
                    assert "rollout_rollback" in kinds, kinds
                    bb_dir = os.path.join(
                        head_mod.get_runtime().session_dir, "blackbox")
                    bundles = [f for f in os.listdir(bb_dir)
                               if f.startswith("blackbox-rollout-guard")
                               and f.endswith(".json")]
                    assert bundles, "rollback wrote no blackbox bundle"
            finally:
                srv.close()
        finally:
            raydp_tpu.stop()
            os.environ.pop("RDT_FAULTS", None)
            os.environ.pop("RDT_SERVE_BATCH_TIMEOUT_MS", None)
            os.environ.pop("RDT_SERVE_HEDGE", None)

    # the guard judged the latency regression, not an error burst
    assert outcome["outcome"] == "rolled_back", outcome
    assert "p99" in outcome["reason"], outcome
    # zero dropped: every seeded request completed, none failed terminally
    assert reports["rollout"]["failed"] == 0, reports["rollout"]
    assert len(results["rollout"]) == len(results["clean"]) == 240
    # byte-identical to the rollout-free run: neither the canary detour nor
    # the rollback re-home may leak into the numbers
    assert np.array_equal(results["clean"], results["rollout"])
    # the canary group is gone: the primary (v1) is the only live version
    # and no replica still carries the canary's bundle
    rep = reports["rollout"]
    assert rep["servable"]["version"] == 1, rep["servable"]
    assert [vr["version"] for vr in rep["versions"]] == [1], rep["versions"]
    assert all(r["version"] == 1 for r in rep["replicas"]), rep["replicas"]


def test_rollout_canary_executor_crash_mid_ramp_stays_unmixed(tmp_path):
    """ISSUE 18 chaos leg (b): the canary's executor CRASHES mid-ramp
    (``nth=2`` on replica guardb-v2-r0, once= sentinel). The in-flight
    dispatch re-routes VERSION-LOCALLY to the canary's surviving sibling —
    the ramp then continues or rolls back on its own judgment, but no
    response ever mixes versions: every answer is checked row-for-row
    against locally computed reference predictions of model A and model B
    (two genuinely different trainings) and must equal exactly one of
    them."""
    import optax

    from raydp_tpu.models import MLP
    from raydp_tpu.serve import ServingSession, load_servable
    from raydp_tpu.train import FlaxEstimator

    rng = np.random.RandomState(11)
    x = rng.random_sample((512, 2))
    y = x @ np.array([2.0, -3.0]) + 1.0
    pdf = pd.DataFrame({"x1": x[:, 0], "x2": x[:, 1], "y": y})
    dir_a = str(tmp_path / "guardb-a")
    dir_b = str(tmp_path / "guardb-b")
    sentinel = str(tmp_path / "rollout_crash.sentinel")

    # the 2nd batch entering canary replica guardb-v2-r0 kills its executor
    # abruptly mid-request; the primary replica colocated on that executor
    # dies with it (both groups must re-route, each within its own version)
    os.environ["RDT_FAULTS"] = (
        f"serve.predict:crash:nth=2:match=|guardb-v2-r0:once={sentinel}")
    os.environ["RDT_SERVE_BATCH_TIMEOUT_MS"] = "10"
    os.environ["RDT_SERVE_HEDGE"] = "0"
    s = _session("serve_rollout_crash")
    try:
        df = s.createDataFrame(pdf, num_partitions=2)
        # two genuinely different models: more epochs move the weights, and
        # the refs-differ assert below keeps the mixing check non-vacuous
        est_a = FlaxEstimator(
            model=MLP(features=(8,), use_batch_norm=False),
            optimizer=optax.adam(1e-2), loss="mse",
            feature_columns=["x1", "x2"], label_column="y",
            batch_size=64, num_epochs=1)
        est_a.fit_on_frame(df)
        est_a.export_serving(dir_a)
        est_b = FlaxEstimator(
            model=MLP(features=(8,), use_batch_norm=False),
            optimizer=optax.adam(1e-2), loss="mse",
            feature_columns=["x1", "x2"], label_column="y",
            batch_size=64, num_epochs=4)
        est_b.fit_on_frame(df)
        est_b.export_serving(dir_b)

        # per-request reference predictions, computed locally through the
        # SAME servable decode/place/apply path the replicas run
        sv_a, sv_b = load_servable(dir_a), load_servable(dir_b)
        batches = []
        refs_a, refs_b = [], []
        for i in range(120):
            j = (2 * i) % 400
            tbl = pa.table({"x1": x[j:j + 2, 0], "x2": x[j:j + 2, 1]})
            batches.append(j)
            refs_a.append(sv_a.predict_table(tbl))
            refs_b.append(sv_b.predict_table(tbl))
        assert not np.array_equal(refs_a[0], refs_b[0]), \
            "models A and B predict identically; mixing check is vacuous"

        srv = ServingSession(dir_a, session=s, name="guardb")
        try:
            got = []
            t = threading.Thread(target=_guard_traffic,
                                 args=(srv, x, 120, got))
            t.start()
            try:
                outcome = srv.rollout(
                    dir_b, tag="crashy-host", initial_weight=0.5,
                    steps=[0.5, 1.0], step_s=10.0, min_samples=4,
                    timeout=180.0)
            finally:
                t.join(timeout=240.0)
            assert not t.is_alive(), "traffic thread hung"
            report = srv.serving_report()
        finally:
            srv.close()
    finally:
        raydp_tpu.stop()
        os.environ.pop("RDT_FAULTS", None)
        os.environ.pop("RDT_SERVE_BATCH_TIMEOUT_MS", None)
        os.environ.pop("RDT_SERVE_HEDGE", None)

    # the injection actually fired, mid-ramp
    assert os.path.exists(sentinel), "crash schedule never fired"
    # zero dropped: the crashed dispatch re-routed (version-locally) and
    # completed; the ramp reached a terminal verdict on its own
    assert outcome["outcome"] in ("promoted", "rolled_back"), outcome
    assert report["failed"] == 0, report
    assert report["rerouted"] >= 1, report
    assert len(got) == 120
    # NO response mixes versions: each answer equals model A's reference or
    # model B's reference for its batch, entirely
    from_a = from_b = 0
    for i, ans in enumerate(got):
        if np.array_equal(ans, refs_a[i]):
            from_a += 1
        elif np.array_equal(ans, refs_b[i]):
            from_b += 1
        else:
            raise AssertionError(
                f"response {i} (batch offset {batches[i]}) matches neither "
                f"version's reference — versions mixed in one response")
    # both versions actually took traffic (the canary held >= min_samples
    # requests before any terminal verdict)
    assert from_a >= 1 and from_b >= 1, (from_a, from_b)


# ==== multi-tenant overload robustness (ISSUE 14) ============================

def _wide_pdf(n=16000):
    rng = np.random.RandomState(0)
    return pd.DataFrame({"k": rng.randint(0, 50, n),
                         "v": rng.randint(0, 1000, n).astype(np.int64)})


def test_spilled_blob_file_lost_mid_join_recovers(tmp_path, monkeypatch):
    """Chaos leg (ROADMAP item 4's missing fault proof): a spilled shuffle
    blob's DISK FILE is deleted mid-join (``store.spill:drop`` — the
    lost-disk model). The reduce side's transparent fault-in misses the
    file, ``_fault_in`` surfaces the typed ``ObjectLostError``, lineage
    recovery regenerates the map blob — byte-identical to a spill-free
    fault-free run, zero orphans. Parquet inputs keep the store holding
    ONLY intermediates, so every spill victim is lineage-recoverable."""
    from raydp_tpu import config as cfg

    monkeypatch.setenv("RDT_ETL_AQE", "0")  # a broadcast join skips spill
    rng = np.random.RandomState(0)
    for side, col in (("L", "v"), ("R", "w")):
        for i in range(2):
            pdf = pd.DataFrame(
                {"k": rng.randint(0, 200, 6000),
                 col: rng.randint(0, 1000, 6000).astype(np.int64)})
            pdf.to_parquet(str(tmp_path / f"{side}{i}.parquet"))

    def run(app, budget=None):
        from raydp_tpu.runtime.object_store import get_client

        s = raydp_tpu.init(
            app, num_executors=2, executor_cores=1, executor_memory="512MB",
            configs={cfg.SPILL_BUDGET_KEY: str(budget)} if budget else None)
        try:
            client = get_client()
            before = client.stats()["num_objects"]
            dfl = s.read.parquet([str(tmp_path / "L0.parquet"),
                                  str(tmp_path / "L1.parquet")])
            dfr = s.read.parquet([str(tmp_path / "R0.parquet"),
                                  str(tmp_path / "R1.parquet")])
            out = dfl.join(dfr, on="k")
            table = s.engine.collect(out._plan).sort_by(
                [("k", "ascending"), ("v", "ascending"), ("w", "ascending")])
            deadline = time.time() + 30
            while time.time() < deadline \
                    and client.stats()["num_objects"] != before:
                time.sleep(0.2)
            report = s.engine.shuffle_stage_report()
            return (_ipc_bytes(table),
                    client.stats()["num_objects"] - before, report)
        finally:
            raydp_tpu.stop()

    base, orphans0, _ = run("spill-join-base")
    assert orphans0 == 0

    sent = str(tmp_path / "spill-drop.sentinel")
    monkeypatch.setenv("RDT_FAULTS", f"store.spill:drop:nth=1:once={sent}")
    got, orphans, report = run("spill-join-chaos", budget=250_000)
    assert os.path.exists(sent), "store.spill drop never fired"
    assert got == base, "recovered join diverged from the fault-free run"
    assert orphans == 0, f"spill-loss recovery orphaned {orphans} objects"
    assert sum(e.get("recovered", 0) for e in report) >= 1, report
    assert sum(e.get("regenerated", 0) for e in report) >= 1, report


def test_flood_and_interactive_tenants_share_pool(tmp_path, monkeypatch):
    """Fairness chaos leg: a flooding tenant (a wide, per-map-delayed
    groupagg) and an interactive tenant (the canonical small groupagg)
    share ONE pool via two engines. The interactive action completes while
    the flood still has queued work (bounded latency — it never waits out
    the flood's queue), both tenants' results are byte-identical to
    uncontended runs, the per-tenant columns surface in load() and the
    stage report, and the store audit shows zero orphans."""
    from raydp_tpu.etl.engine import Engine

    # uncontended baselines (fault-free, fixed pool)
    s = _session3("chaos-fair-base")
    try:
        small = _frame(s)
        out_s = small.groupBy("k").agg(F.sum("v").alias("s"),
                                       F.count("v").alias("n"))
        base_small = _ipc_bytes(s.engine.collect(out_s._plan)
                                .sort_by([("k", "ascending")]))
        wide = s.createDataFrame(_wide_pdf(), num_partitions=48)
        out_w = wide.groupBy("k").agg(F.sum("v").alias("s"),
                                      F.count("v").alias("n"))
        base_wide = _ipc_bytes(s.engine.collect(out_w._plan)
                               .sort_by([("k", "ascending")]))
    finally:
        raydp_tpu.stop()

    # contended run: per-map delay stretches the flood (48 delayed maps
    # over 12 slots = several waves) so the interactive action demonstrably
    # overlaps it
    monkeypatch.setenv("RDT_FAULTS",
                       "executor.run_task:delay:ms=200:match=|mt-")
    s = _session3("chaos-fair")
    try:
        from raydp_tpu.runtime.object_store import get_client
        client = get_client()
        small = _frame(s)
        out_s = small.groupBy("k").agg(F.sum("v").alias("s"),
                                       F.count("v").alias("n"))
        # the flood is a SECOND tenant on the same pool: a second engine
        # over the session's executors, wide input (16 delayed maps)
        flood_eng = Engine(s.engine.pool,
                           shuffle_partitions=s.engine.shuffle_partitions,
                           owner=s.engine.owner, tenant="flood")
        wide = s.createDataFrame(_wide_pdf(), num_partitions=48)
        out_w = wide.groupBy("k").agg(F.sum("v").alias("s"),
                                      F.count("v").alias("n"))
        before = client.stats()["num_objects"]
        box = {}

        def flood():
            try:
                box["wide"] = _ipc_bytes(flood_eng.collect(out_w._plan)
                                         .sort_by([("k", "ascending")]))
            except Exception as e:  # noqa: BLE001 - asserted below
                box["error"] = e

        t = threading.Thread(target=flood)
        t.start()
        deadline = time.time() + 30
        while time.time() < deadline \
                and (s.engine.pool.load()["tenants"]
                     .get("flood", {}).get("queued", 0)) < 4:
            time.sleep(0.02)  # the flood has saturated + queued
        t0 = time.monotonic()
        got_small = _ipc_bytes(s.engine.collect(out_s._plan)
                               .sort_by([("k", "ascending")]))
        inter_wall = time.monotonic() - t0
        load_at_finish = s.engine.pool.load()
        t.join(timeout=300)
        assert "error" not in box, box.get("error")
        # bounded latency: the interactive action finished while the flood
        # still had queued work — it shared slots instead of queueing behind
        flood_row = load_at_finish["tenants"].get("flood", {})
        assert flood_row.get("queued", 0) > 0, load_at_finish
        assert inter_wall < 20.0, f"interactive starved: {inter_wall:.1f}s"
        # per-tenant observability: both tenants' dispatch counts surface,
        # and the stage report carries the tenant column
        tenants = load_at_finish["tenants"]
        assert tenants[s.master_name]["dispatched"] >= 1
        assert tenants["flood"]["dispatched"] >= 1
        rep = s.engine.shuffle_stage_report() + \
            flood_eng.shuffle_stage_report()
        assert {e["tenant"] for e in rep} >= {s.master_name, "flood"}
        # accepted results byte-identical to the uncontended runs
        assert got_small == base_small
        assert box["wide"] == base_wide
        deadline = time.time() + 30
        while time.time() < deadline \
                and client.stats()["num_objects"] != before:
            time.sleep(0.25)
        orphans = client.stats()["num_objects"] - before
        assert orphans == 0, f"contended run orphaned {orphans} objects"
    finally:
        raydp_tpu.stop()


def test_serving_overload_burst_sheds_typed(tmp_path, monkeypatch):
    """Serving overload chaos leg: a burst far past RDT_SERVE_MAX_QUEUE
    against a deliberately slowed replica sheds with the typed retriable
    ServingOverloaded — the dispatcher stays alive (accepted requests all
    complete, a post-burst request is served), accepted results are
    byte-identical to an uncontended run, and the report shows
    failed == shed only."""
    import optax

    from raydp_tpu.models import MLP
    from raydp_tpu.serve import ServingOverloaded, ServingSession
    from raydp_tpu.train import FlaxEstimator

    rng = np.random.RandomState(11)
    x = rng.random_sample((256, 2))
    y = x @ np.array([2.0, -3.0]) + 1.0
    pdf = pd.DataFrame({"x1": x[:, 0], "x2": x[:, 1], "y": y})
    export_dir = str(tmp_path / "overload-servable")

    monkeypatch.setenv("RDT_SERVE_BATCH_TIMEOUT_MS", "5")
    # armed BEFORE init so the spawned executors (where serve.predict
    # fires) inherit the delay; it slows every replica apply by 120ms,
    # which cannot change the jitted numbers — only the queue dynamics
    monkeypatch.setenv("RDT_FAULTS", "serve.predict:delay:ms=120")
    s = _session("serve_overload")
    try:
        df = s.createDataFrame(pdf, num_partitions=2)
        est = FlaxEstimator(model=MLP(features=(8,), use_batch_norm=False),
                            optimizer=optax.adam(1e-2), loss="mse",
                            feature_columns=["x1", "x2"], label_column="y",
                            batch_size=64, num_epochs=1)
        est.fit_on_frame(df)
        est.export_serving(export_dir)

        # uncontended reference predictions (shedding off)
        monkeypatch.setenv("RDT_SERVE_MAX_QUEUE", "0")
        with ServingSession(export_dir, session=s, name="ref",
                            num_replicas=1) as ref:
            expect = [ref.predict({"x1": x[i:i + 2, 0],
                                   "x2": x[i:i + 2, 1]}, timeout=60.0)
                      for i in range(0, 64, 2)]

        # overload run: the same slow replicas + a tight queue bound
        monkeypatch.setenv("RDT_SERVE_MAX_QUEUE", "6")
        srv = ServingSession(export_dir, session=s, name="overload",
                             num_replicas=1)
        try:
            accepted, shed = [], 0
            for i in range(0, 64, 2):
                try:
                    accepted.append(
                        (i // 2, srv.predict_async({"x1": x[i:i + 2, 0],
                                                    "x2": x[i:i + 2, 1]})))
                except ServingOverloaded:
                    shed += 1
            assert shed >= 1, "burst never shed"
            assert len(accepted) >= 6
            for idx, fut in accepted:
                got = fut.result(timeout=120.0)
                assert np.array_equal(got, expect[idx]), idx
            rep = srv.serving_report()
            assert rep["shed"] == shed
            assert rep["failed"] == rep["shed"], rep  # failed == shed ONLY
            # the dispatcher survived the burst: a fresh request serves
            tail = srv.predict({"x1": x[:2, 0], "x2": x[:2, 1]},
                               timeout=60.0)
            assert np.array_equal(tail, expect[0])
            from raydp_tpu import metrics
            assert "overload_shed" in [e["kind"] for e in metrics.events()]
        finally:
            srv.close()
    finally:
        raydp_tpu.stop()


def test_admission_composes_with_autoscale_and_drain(tmp_path, monkeypatch):
    """Admission chaos leg: a flooding tenant pushes the pool backlog past
    RDT_POOL_MAX_QUEUED so a second action PARKS at admission; the
    autoscaler (armed, fast cadence) sees the parked demand and grows the
    pool; a concurrent graceful drain retires an executor mid-flood. Both
    actions complete byte-identical to uncontended baselines, the parked
    action was admitted (never rejected), and the store audit shows zero
    orphans."""
    s = _session("chaos-admit-base")
    try:
        df = _frame(s)
        out = df.groupBy("k").agg(F.sum("v").alias("s"),
                                  F.count("v").alias("n"))
        base_small = _ipc_bytes(s.engine.collect(out._plan)
                                .sort_by([("k", "ascending")]))
        wide = s.createDataFrame(_wide_pdf(), num_partitions=48)
        out_w = wide.groupBy("k").agg(F.sum("v").alias("s"),
                                      F.count("v").alias("n"))
        base_wide = _ipc_bytes(s.engine.collect(out_w._plan)
                               .sort_by([("k", "ascending")]))
    finally:
        raydp_tpu.stop()

    monkeypatch.setenv("RDT_POOL_MAX_QUEUED", "8")
    monkeypatch.setenv("RDT_ADMIT_TIMEOUT_S", "120")
    monkeypatch.setenv("RDT_POOL_SCALE_INTERVAL_S", "0.2")
    monkeypatch.setenv("RDT_POOL_SCALE_UP_S", "0.3")
    monkeypatch.setenv("RDT_POOL_IDLE_S", "60")
    monkeypatch.setenv("RDT_POOL_COOLDOWN_S", "0.5")
    monkeypatch.setenv("RDT_FAULTS",
                       "executor.run_task:delay:ms=200:match=|mt-")
    s = _session3("chaos-admit")
    try:
        from raydp_tpu.runtime.object_store import get_client
        client = get_client()
        auto = s.autoscale(min_size=1, max_size=4)
        df = _frame(s)
        out = df.groupBy("k").agg(F.sum("v").alias("s"),
                                  F.count("v").alias("n"))
        wide = s.createDataFrame(_wide_pdf(), num_partitions=48)
        out_w = wide.groupBy("k").agg(F.sum("v").alias("s"),
                                      F.count("v").alias("n"))
        before = client.stats()["num_objects"]
        box = {}

        def flood():
            try:
                box["wide"] = _ipc_bytes(s.engine.collect(out_w._plan)
                                         .sort_by([("k", "ascending")]))
            except Exception as e:  # noqa: BLE001 - asserted below
                box["flood_error"] = e

        def late():
            try:
                box["small"] = _ipc_bytes(s.engine.collect(out._plan)
                                          .sort_by([("k", "ascending")]))
            except Exception as e:  # noqa: BLE001 - asserted below
                box["late_error"] = e

        tf = threading.Thread(target=flood)
        tf.start()
        deadline = time.time() + 30
        while time.time() < deadline \
                and s.engine.pool.load()["queued"] <= 8:
            time.sleep(0.02)  # flood backlog past the admission bound
        tl = threading.Thread(target=late)
        tl.start()
        # the late action parks at admission (visible in load())
        deadline = time.time() + 20
        parked_seen = 0
        while time.time() < deadline:
            parked_seen = max(parked_seen, s.engine.pool.load()["parked"])
            if parked_seen:
                break
            time.sleep(0.02)
        # concurrent drain while the flood runs and the late action parks
        s.retire_executor(s.executors[-1].name)
        tf.join(timeout=300)
        tl.join(timeout=300)
        assert "flood_error" not in box, box.get("flood_error")
        assert "late_error" not in box, box.get("late_error")
        assert parked_seen > 0, "late action never parked at admission"
        assert box["wide"] == base_wide
        assert box["small"] == base_small
        # the autoscaler grew for the parked/queued demand
        assert any(e["direction"] == "up" for e in auto.events), auto.events
        deadline = time.time() + 30
        while time.time() < deadline \
                and client.stats()["num_objects"] != before:
            time.sleep(0.25)
        orphans = client.stats()["num_objects"] - before
        assert orphans == 0, f"admission+scale+drain orphaned {orphans}"
        from raydp_tpu import metrics
        snap = metrics.snapshot()["counters"]
        assert snap.get("pool_admission_parked_total", {}), snap
        assert not snap.get("pool_admission_rejects_total", {}), \
            "the parked action was rejected instead of admitted"
    finally:
        raydp_tpu.stop()


# ---------------------------------------------------------------------------
# continuous pipelines (ISSUE 15): the streaming fault matrix
# ---------------------------------------------------------------------------

def _run_stream_windows(app, epochs=5, rows=1200):
    """One full session driving a windowed continuous pipeline; returns
    (list of (start, end, window ipc bytes), epoch result bytes, report).
    Window tables are already key-sorted by the pipeline (the groupagg
    row-order caveat of _run_groupagg, handled once in _merge_window)."""
    from raydp_tpu import stream
    from raydp_tpu.etl.expressions import col

    def make(epoch):
        rng = np.random.RandomState(epoch)
        return pa.table({
            "k": rng.randint(0, 16, rows),
            "v": rng.randint(0, 1000, rows).astype(np.int64),
        })

    s = _session(app)
    try:
        from raydp_tpu.runtime.object_store import get_client
        client = get_client()
        before = client.stats()["num_objects"]
        pipe = stream.read_stream(
            stream.SyntheticSource(make, max_epochs=epochs)).transform(
            lambda df: df.filter(col("v") % 7 != 0)).window(
            size=3, slide=1, keys=["k"], aggs={"v": ["sum", "count"]})
        wins, epochs_b = [], []
        for er in pipe.epochs():
            epochs_b.append(_ipc_bytes(er.table()))
            wins.extend((w.start, w.end, _ipc_bytes(w.table))
                        for w in er.windows)
        rep = pipe.report()
        pipe.close()
        deadline = time.time() + 30
        while time.time() < deadline \
                and client.stats()["num_objects"] != before:
            time.sleep(0.25)
        orphans = client.stats()["num_objects"] - before
        return wins, epochs_b, rep, orphans
    finally:
        raydp_tpu.stop()


def test_stream_executor_crash_mid_epoch_byte_identical(tmp_path,
                                                        monkeypatch):
    """An executor crash in the middle of an epoch's engine action: the
    lineage plane re-runs the lost tasks INSIDE the epoch (the stream layer
    never notices), and every epoch result and window merge is
    byte-identical to the fault-free run with zero orphans."""
    base_w, base_e, _, orphans0 = _run_stream_windows("stream-crash-base")
    assert orphans0 == 0

    crash_s = str(tmp_path / "stream-crash.sentinel")
    monkeypatch.setenv(
        "RDT_FAULTS", f"executor.run_task:crash:nth=4:once={crash_s}")
    got_w, got_e, rep, orphans = _run_stream_windows("stream-crash")
    assert os.path.exists(crash_s), "injected crash never fired"
    assert got_e == base_e, "epoch results diverged after the crash"
    assert got_w == base_w, "window results diverged after the crash"
    assert orphans == 0, f"crash replay orphaned {orphans} store objects"


def test_stream_epoch_drop_replays_exactly_once(tmp_path, monkeypatch):
    """The stream's own fault site: ``stream.epoch:drop`` loses a freshly
    sealed epoch's partial blobs post-commit (the store-host-died model for
    streams). The window merges spanning the lost epoch must re-derive it
    from the source journal — results byte-identical to the unfaulted run,
    each epoch contributing exactly once, zero orphans."""
    base_w, base_e, base_rep, _ = _run_stream_windows("stream-drop-base")
    assert base_rep["replays"] == 0

    sent = str(tmp_path / "stream-drop.sentinel")
    monkeypatch.setenv("RDT_FAULTS",
                       f"stream.epoch:drop:nth=2:once={sent}")
    got_w, got_e, rep, orphans = _run_stream_windows("stream-drop")
    assert os.path.exists(sent), "injected drop never fired"
    assert rep["replays"] >= 1, "the lost epoch was never replayed"
    assert got_w == base_w, "window results diverged after the drop"
    assert got_e == base_e
    assert orphans == 0, f"epoch replay orphaned {orphans} store objects"
