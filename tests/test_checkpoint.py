"""Checkpoint-format unit tests: raw-byte entry encoding (extension dtypes
like bfloat16 survive npz), completeness detection for torn step dirs, and
orbax/sharded format selection in ``restore``."""

import json
import os

import numpy as np

from raydp_tpu.train import checkpoint as ckpt


def test_raw_roundtrip_bfloat16(tmp_path):
    import jax.numpy as jnp

    arr = np.arange(6, dtype=np.float32).reshape(2, 3).astype(jnp.bfloat16)
    path = str(tmp_path / "s.npz")
    np.savez(path, a0=ckpt._raw(arr))
    e = {"arr": "a0", "index": [[0, 2], [0, 3]], "dtype": "bfloat16",
         "shape": [2, 3]}
    out = ckpt._entry_array(np.load(path), e)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_array_equal(out.astype(np.float32),
                                  arr.astype(np.float32))


def test_raw_roundtrip_scalar(tmp_path):
    arr = np.int64(7)
    path = str(tmp_path / "s.npz")
    np.savez(path, a0=ckpt._raw(np.asarray(arr)))
    e = {"arr": "a0", "index": [], "dtype": "int64", "shape": []}
    out = ckpt._entry_array(np.load(path), e)
    assert out.shape == () and int(out) == 7


def test_torn_step_dirs_are_skipped(tmp_path):
    """A dir a dying gang created but never wrote (or wrote partially, no
    COMPLETE) must not be chosen by restore."""
    good = ckpt.save(str(tmp_path), {"a": np.arange(3.0)}, step=0)
    assert good is not None

    torn_empty = tmp_path / "step_1"          # created pre-barrier, empty
    torn_empty.mkdir()
    torn_partial = tmp_path / "step_2"        # manifests but no COMPLETE
    torn_partial.mkdir()
    (torn_partial / "manifest_0.json").write_text(json.dumps([]))

    steps = ckpt._step_dirs(str(tmp_path))
    assert [s for s, _ in steps] == [0]
    restored = ckpt.restore(str(tmp_path), {"a": np.zeros(3)})
    assert restored is not None
    state, step = restored
    assert step == 0
    np.testing.assert_array_equal(state["a"], np.arange(3.0))


def test_restore_reads_sharded_format_single_process(tmp_path):
    """A driver process can reassemble a gang's sharded checkpoint: write the
    format by hand (two 'processes', split rows) and restore with a template."""
    step_dir = tmp_path / "step_3"
    step_dir.mkdir()
    full = np.arange(8, dtype=np.float32).reshape(4, 2)
    for p, rows in ((0, (0, 2)), (1, (2, 4))):
        np.savez(str(step_dir / f"shard_{p}.npz"),
                 a0=ckpt._raw(full[rows[0]:rows[1]]))
        manifest = [{"key": "['w']", "arr": "a0",
                     "index": [[rows[0], rows[1]], [0, 2]],
                     "shape": [4, 2], "dtype": "float32"}]
        (step_dir / f"manifest_{p}.json").write_text(json.dumps(manifest))
    (step_dir / "COMPLETE").touch()

    restored = ckpt.restore(str(tmp_path), {"w": np.zeros((4, 2))})
    assert restored is not None
    state, step = restored
    assert step == 3
    np.testing.assert_array_equal(state["w"], full)


def test_warn_if_reused_dir(tmp_path):
    """A fresh fit pointed at a dir holding an earlier run's step_* dirs must
    say so up front (advisor r4): retention/retry are scoped to this run, but
    a later resume without max_step would adopt the foreign steps silently."""
    import logging

    records = []
    handler = logging.Handler()
    handler.emit = records.append  # the package logger has propagate=False
    lg = logging.getLogger("raydp_tpu.train.checkpoint")
    lg.addHandler(handler)
    try:
        ckpt.warn_if_reused_dir(str(tmp_path))        # empty: silent
        assert not records
        (tmp_path / "step_7").mkdir()                 # even a torn dir counts
        ckpt.warn_if_reused_dir(str(tmp_path))
        assert any("already contains" in r.getMessage() for r in records)
    finally:
        lg.removeHandler(handler)
