"""Generic Cluster/ClusterMaster ABCs: the external-engine plug surface.

Parity: reference services.py:22-90 — engine-agnostic master+worker lifecycle
("such as SparkCluster, FlinkCluster") with the fail-safe add_worker contract.
The built-in ETL engine rides the same surface (EtlCluster, driven by the
Session), so these tests prove a third-party engine can too.
"""

import time

import pytest


def _wait_gone(rt, name, timeout=30.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if rt.get_actor(name) is None:
            return
        time.sleep(0.2)
    raise AssertionError(f"actor {name} still alive")


class ToyMaster:
    def __init__(self, tag):
        self.tag = tag

    def info(self):
        return f"master-{self.tag}"


class ToyWorker:
    def __init__(self, master_name, index):
        self.master_name = master_name
        self.index = index

    def whoami(self):
        return f"{self.master_name}/worker{self.index}"


def test_etl_cluster_lifecycle(runtime):
    from raydp_tpu.cluster import EtlCluster

    cluster = EtlCluster("abc-app")
    try:
        assert cluster.get_cluster_url() == "abc-app_MASTER"
        assert runtime.get_actor("abc-app_MASTER") is not None
        cluster.add_worker({"CPU": 1.0})
        cluster.add_worker({"CPU": 1.0})
        assert cluster.num_workers == 2
        assert len(cluster.workers) == 2
        # workers are live executors bound to the master
        assert cluster.workers[0].ping() == "pong"
        cluster.remove_worker()
        assert cluster.num_workers == 1
    finally:
        cluster.stop()
    assert cluster.workers == []
    _wait_gone(runtime, "abc-app_MASTER")


def test_external_engine_subclass(runtime):
    """A non-ETL engine implements the same ABCs and gets supervised actors,
    naming, and teardown from the substrate."""
    from raydp_tpu.cluster import Cluster

    class ToyCluster(Cluster):
        def __init__(self):
            self.master_handle = None
            self.worker_handles = []
            super().__init__({"CPU": 0.5})

        def _set_up_master(self, resources, kwargs):
            self.master_handle = runtime.create_actor(
                ToyMaster, ("t1",), name="toy-master", resources=resources)

        def _set_up_worker(self, resources, kwargs):
            i = len(self.worker_handles)
            self.worker_handles.append(runtime.create_actor(
                ToyWorker, ("toy-master", i), name=f"toy-worker-{i}",
                resources=resources))

        def get_cluster_url(self):
            return "toy://toy-master"

        def stop(self):
            for h in self.worker_handles:
                try:
                    h.kill(no_restart=True)
                except Exception:
                    pass
            self.worker_handles = []
            if self.master_handle is not None:
                self.master_handle.kill(no_restart=True)
                self.master_handle = None

    cluster = ToyCluster()
    try:
        assert cluster.master_handle.info() == "master-t1"
        cluster.add_worker({"CPU": 0.5})
        cluster.add_worker({"CPU": 0.5})
        assert cluster.worker_handles[1].whoami() == "toy-master/worker1"
        assert cluster.num_workers == 2
    finally:
        cluster.stop()
    _wait_gone(runtime, "toy-master")


def test_add_worker_failure_stops_cluster(runtime):
    """The fail-safe contract (reference services.py:40-52): a worker that
    cannot start tears the whole cluster down rather than leaking it."""
    from raydp_tpu.cluster import Cluster

    stopped = []

    class FlakyCluster(Cluster):
        def _set_up_master(self, resources, kwargs):
            self.master_handle = runtime.create_actor(
                ToyMaster, ("t2",), name="flaky-master")

        def _set_up_worker(self, resources, kwargs):
            raise RuntimeError("no room for workers")

        def get_cluster_url(self):
            return "toy://flaky"

        def stop(self):
            stopped.append(True)
            if getattr(self, "master_handle", None) is not None:
                self.master_handle.kill(no_restart=True)
                self.master_handle = None

    cluster = FlakyCluster(None)
    with pytest.raises(RuntimeError, match="no room"):
        cluster.add_worker({"CPU": 1.0})
    assert stopped == [True]
    _wait_gone(runtime, "flaky-master")
