"""Data-plane tests (parity: reference test_spark_cluster.py:150-366 conversion
tests and test_from_spark.py ownership tests)."""

import time

import numpy as np
import pytest

import raydp_tpu
from raydp_tpu.data import (
    DeviceFeed, DistributedDataset, from_frame, from_frame_recoverable, to_frame,
)
from raydp_tpu.data.feed import HostBatchIterator, ShardSpec
from raydp_tpu.etl.expressions import col


def _make_df(session, n=1000, parts=4):
    return session.range(n, num_partitions=parts).withColumn(
        "x", col("id") * 2).withColumn("y", col("id") % 7)


def test_from_frame_eager(session):
    ds = from_frame(_make_df(session))
    assert ds.count() == 1000
    assert ds.num_blocks() == 4
    assert set(ds.schema.names) == {"id", "x", "y"}
    table = ds.to_arrow()
    assert table.num_rows == 1000


def test_from_frame_recoverable_and_release(session):
    ds = from_frame_recoverable(_make_df(session))
    assert ds.count() == 1000
    assert ds.num_blocks() == 4
    # all blocks fetched through the executor data plane into the store
    t0 = ds.get_block(0)
    assert t0.num_rows > 0
    ds.release()
    assert ds.num_blocks() == 0
    assert session.cached_frames() == []


def test_recoverable_survives_executor_crash(session):
    ds = from_frame_recoverable(_make_df(session, n=400))
    before = ds.count()
    # wipe caches AND the already-fetched store refs: full refetch path
    for b in ds._blocks:
        b.ref = None
    for h in session.executors:
        try:
            h.call("crash")
        except Exception:
            pass
    deadline = time.time() + 60
    total = None
    while time.time() < deadline:
        try:
            total = sum(ds.get_block(i).num_rows for i in range(ds.num_blocks()))
            break
        except Exception:
            time.sleep(0.5)
    assert total == before == 400


def test_to_frame_roundtrip(session):
    ds = from_frame(_make_df(session, n=300, parts=3))
    df2 = to_frame(ds, session)
    assert df2.count() == 300
    out = df2.filter(col("x") >= 400).count()
    assert out == 300 - 200
    # master holds the refs (parity: add_objects, ray_cluster_master.py:222-226)
    assert len(session.master.holders()) == 1


def test_dataset_ownership_survives_stop():
    """parity: stop_spark(cleanup_data=False) keeps converted data alive
    (context.py:152-162, dataset.py:137-158, tests/test_from_spark.py)."""
    session = raydp_tpu.init("own-test", num_executors=2, executor_cores=1,
                             executor_memory="256MB")
    try:
        ds = from_frame_recoverable(_make_df(session, n=200, parts=2))
        assert ds.count() == 200
        ds.transfer_to_master()
        raydp_tpu.stop(cleanup_data=False)  # executors die; master survives
        # blocks still resolvable from the store
        total = sum(ds.get_block(i).num_rows for i in range(ds.num_blocks()))
        assert total == 200
    finally:
        raydp_tpu.stop(cleanup_data=True)


def test_random_shuffle_distributed(session, monkeypatch):
    """random_shuffle runs on the executors: the driver must move only refs
    (VERDICT r3 Weak #3 — the old path pulled every block through the
    driver), the result is a uniform permutation of the same rows, and a
    fixed seed is deterministic (lineage-safe)."""
    from raydp_tpu.runtime.object_store import get_client

    ds = from_frame(_make_df(session))
    client = get_client()
    real_get = client.get

    def no_get(*a, **k):
        raise AssertionError(
            "driver materialized a block during random_shuffle")

    monkeypatch.setattr(client, "get", no_get)
    try:
        out = ds.random_shuffle(seed=7)
    finally:
        monkeypatch.setattr(client, "get", real_get)

    assert out.count() == 1000
    inp = ds.to_arrow().to_pandas().sort_values("id").reset_index(drop=True)
    shuf = out.to_arrow().to_pandas()
    assert shuf.sort_values("id").reset_index(drop=True).equals(inp)
    assert list(shuf["id"]) != sorted(shuf["id"])  # actually permuted
    # determinism: same seed → same global row order; different seed → different
    again = ds.random_shuffle(seed=7).to_arrow().column("id").to_pylist()
    assert again == shuf["id"].tolist()
    other = ds.random_shuffle(seed=8).to_arrow().column("id").to_pylist()
    assert other != again


def test_split_shards_balanced(session):
    ds = from_frame(_make_df(session, n=1003, parts=4))
    plans = ds.split_shards(world_size=3)
    sizes = [sum(n for _, _, n in plan) for plan in plans]
    assert len(set(sizes)) == 1  # every rank equal (SPMD requirement)
    assert sizes[0] == -(-1003 // 3)


def test_host_batch_iterator(session):
    ds = from_frame(_make_df(session, n=1000, parts=4))
    it = HostBatchIterator(
        ds, batch_size=128,
        columns={"feat": (["x", "y"], np.float32), "label": ("id", np.float32)},
        shuffle=True, seed=1)
    batches = list(it)
    assert len(batches) == 1000 // 128
    for b in batches:
        assert b["feat"].shape == (128, 2)
        assert b["feat"].dtype == np.float32
        assert b["label"].shape == (128,)


def test_device_feed_sharded(session):
    import jax
    from jax.sharding import Mesh

    devices = np.array(jax.devices()[:8]).reshape(8)
    mesh = Mesh(devices, ("data",))
    ds = from_frame(_make_df(session, n=2048, parts=4))
    feed = DeviceFeed(
        ds, batch_size=256,
        columns={"feat": (["x", "y"], np.float32), "label": ("id", np.float32)},
        mesh=mesh, shuffle=False)
    n = 0
    for batch in feed:
        assert batch["feat"].shape == (256, 2)
        # sharded over the data axis: each device holds 256/8 rows
        db = batch["feat"].sharding.shard_shape(batch["feat"].shape)
        assert db[0] == 256 // 8
        n += 1
    assert n == 2048 // 256


def test_shard_spec_feed(session):
    ds = from_frame(_make_df(session, n=600, parts=3))
    plans = ds.split_shards(2)
    it = HostBatchIterator(
        ds, batch_size=100, columns={"label": ("id", np.int64)},
        shard=ShardSpec(plans[0]), shuffle=False)
    rows = sum(b["label"].shape[0] for b in it)
    assert rows == 300


def test_split_shards_more_ranks_than_blocks(session):
    """More gang workers than dataset blocks: the shard plan wraps around
    (ranks re-read block prefixes) so every rank still gets the same sample
    count — the reference covers this via its sequential-model test with
    num_workers > partitions (test_torch_sequential.py:23-54)."""
    df = _make_df(session, n=1000, parts=2)
    ds = from_frame(df)
    assert ds.num_blocks() == 2
    plans = ds.split_shards(world_size=5)
    counts = [sum(n for _, _, n in plan) for plan in plans]
    assert len(set(counts)) == 1  # equal share per rank
    assert counts[0] == 1000 // 5
    for plan in plans:
        for block_idx, off, length in plan:
            assert 0 <= block_idx < 2
            assert off >= 0 and length > 0
            assert off + length <= ds.block_sizes()[block_idx]


def test_to_torch_dataset_bridge(session):
    """The torch bridge (reference TorchMLDataset parity,
    torch_ml_dataset.py:30-67): batched (features, label) CPU tensors over
    the native host feed, len() in batches, shard selection for DDP ranks."""
    import torch

    from raydp_tpu.data import to_torch_dataset

    ds = from_frame(_make_df(session, n=500, parts=2))
    tds = to_torch_dataset(ds, feature_columns=["x", "y"], label_column="id",
                           batch_size=100, label_dtype=np.int64)
    assert len(tds) == 5
    batches = list(tds)
    assert len(batches) == 5
    feats, labels = batches[0]
    assert isinstance(feats, torch.Tensor) and feats.shape == (100, 2)
    assert labels.dtype == torch.int64 and labels.shape == (100,)
    total = torch.cat([b[1] for b in batches])
    assert sorted(total.tolist()) == list(range(500))

    # per-rank shards partition the rows
    r0 = to_torch_dataset(ds, ["x"], "id", batch_size=50,
                          label_dtype=np.int64, world_size=2, rank=0)
    r1 = to_torch_dataset(ds, ["x"], "id", batch_size=50,
                          label_dtype=np.int64, world_size=2, rank=1)
    ids0 = torch.cat([b[1] for b in r0]).tolist()
    ids1 = torch.cat([b[1] for b in r1]).tolist()
    assert len(ids0) == len(ids1) == 250
    assert not set(ids0) & set(ids1)

    # a stock DataLoader consumes it with batch_size=None (pre-batched)
    loader = torch.utils.data.DataLoader(tds, batch_size=None)
    first = next(iter(loader))
    assert first[0].shape == (100, 2)

    # shuffle=True must walk a DIFFERENT batch order each epoch (the
    # external-loop analogue of DeviceFeed.set_epoch)
    sds = to_torch_dataset(ds, ["x"], "id", batch_size=100,
                           label_dtype=np.int64, shuffle=True, seed=7)
    e0 = torch.cat([b[1] for b in sds]).tolist()
    e1 = torch.cat([b[1] for b in sds]).tolist()
    assert sorted(e0) == sorted(e1) == list(range(500))
    assert e0 != e1

    # num_workers=2: the stripe split must yield each batch exactly once
    # per epoch (not once per worker)
    wloader = torch.utils.data.DataLoader(tds, batch_size=None,
                                          num_workers=2)
    ids = torch.cat([b[1] for b in wloader]).tolist()
    assert sorted(ids) == list(range(500))


def test_to_tf_dataset_bridge(session):
    """The tf.data bridge (reference to_tf parity, tf/estimator.py:179-199):
    batched (features, label) tensors, ragged tail declared in the
    signature."""
    import tensorflow as tf

    from raydp_tpu.data import to_tf_dataset

    ds = from_frame(_make_df(session, n=250, parts=2))
    tfds = to_tf_dataset(ds, feature_columns=["x", "y"], label_column="id",
                         batch_size=100, label_dtype=np.int64)
    batches = list(tfds)
    assert [int(b[0].shape[0]) for b in batches] == [100, 100, 50]
    assert batches[0][0].dtype == tf.float32
    ids = np.concatenate([b[1].numpy() for b in batches])
    assert sorted(ids.tolist()) == list(range(250))
