"""Distributed data plane: per-node payload hosting + direct owner fetch.

Parity model: the reference gives every node its own plasma store; readers
fetch blocks from the node that holds them and the scheduler sees locality
(RayDPExecutor.scala:271-287 ``getBlockLocations``, RayDatasetRDD.scala:48-56
preferred locations). Here a node agent in isolated-store mode hosts its
machine's payload plane; these tests prove payload bytes are written on the
owning node, served node→node without transiting the head, purged on node
death, and that the engine schedules ref-reading tasks onto the owner's node.
"""

import os
import signal
import subprocess
import sys
import time

import numpy as np
import pyarrow as pa
import pytest

from raydp_tpu.runtime.object_store import HEAD_HOST


class Writer:
    def put_table(self, n):
        from raydp_tpu.runtime.object_store import get_client
        t = pa.table({"x": np.arange(n, dtype=np.int64)})
        return get_client().put(t)

    def read_rows(self, ref):
        from raydp_tpu.runtime.object_store import get_client
        return get_client().get(ref).num_rows

    def host_id(self):
        from raydp_tpu.runtime.object_store import get_client
        return get_client().host_id


def _start_isolated_agent(head_url, cpus=4.0):
    env = dict(os.environ)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env["RDT_STORE_ISOLATED"] = "1"
    env["RDT_ARENA_FREE_GRACE_S"] = "0"  # immediate reclamation for asserts
    proc = subprocess.Popen(
        [sys.executable, "-m", "raydp_tpu.runtime.node_agent",
         "--head", head_url, "--cpus", str(cpus)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        start_new_session=True)
    return proc


def _kill(proc):
    try:
        os.killpg(proc.pid, signal.SIGKILL)
    except (ProcessLookupError, PermissionError):
        try:
            proc.kill()
        except ProcessLookupError:
            pass


def _wait_store_host(rt, timeout=30.0):
    """The agent's node id once its payload plane is announced."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        if rt.store_hosts:
            return next(iter(rt.store_hosts))
        time.sleep(0.2)
    raise TimeoutError("agent never registered its store host")


def test_payloads_live_on_owner_node_and_transfer_direct(runtime):
    """An actor on an isolated node writes locally; the driver reads the
    payload with ONE hop to the agent — the head's payload RPC counter stays
    flat, and the bytes demonstrably occupy the node's arena."""
    rt = runtime
    agent = _start_isolated_agent(rt.server.url)
    try:
        node_id = _wait_store_host(rt)
        h = rt.create_actor(Writer, name="w-iso", node_id=node_id,
                            resources={"CPU": 1.0})
        assert h.host_id() == node_id  # data-plane env reached the child

        ref = h.put_table(4096)
        seg, size, kind, offset, host_id, payload_addr = \
            rt.store_server.lookup(ref.id)
        assert host_id == node_id
        assert payload_addr, "isolated writer must record its payload server"

        agent_client = rt.node_agents[node_id]
        stats = agent_client.call("store_arena_stats")
        if stats is not None:  # native arena present on the node
            assert offset >= 0
            assert stats["bytes_in_use"] >= size

        base = rt.store_server.payload_rpc_count
        table = rt.store_client.get(ref)  # driver read: direct node fetch
        assert table.num_rows == 4096
        assert table["x"][4095].as_py() == 4095
        assert rt.store_server.payload_rpc_count == base, \
            "payload transited the head"

        # same-node reader maps it zero-copy (no cross-machine hop at all)
        assert h.read_rows(ref) == 4096

        # free releases the payload ON the owning node
        rt.store_client.free([ref])
        assert not rt.store_client.contains(ref)
        if stats is not None:
            agent_client.call("store_reap")
            after = agent_client.call("store_arena_stats")
            assert after["bytes_in_use"] < stats["bytes_in_use"]
    finally:
        _kill(agent)


def test_head_objects_still_readable_from_isolated_node(runtime):
    """The reverse direction: a driver-written object is fetched by an
    isolated-node actor from the head's plane (the head IS that object's
    owner node — one hop, by design)."""
    rt = runtime
    agent = _start_isolated_agent(rt.server.url)
    try:
        node_id = _wait_store_host(rt)
        t = pa.table({"x": np.arange(128, dtype=np.int64)})
        ref = rt.store_client.put(t)
        _, _, _, _, host_id, _ = rt.store_server.lookup(ref.id)
        assert host_id == HEAD_HOST
        h = rt.create_actor(Writer, name="r-iso", node_id=node_id,
                            resources={"CPU": 1.0})
        assert h.read_rows(ref) == 128
    finally:
        _kill(agent)


def test_node_death_purges_hosted_objects(runtime):
    """Killing the agent is node death: its payloads are unreachable, so the
    head drops their table entries — readers fail fast into lineage recovery
    instead of timing out against a dead payload server."""
    rt = runtime
    agent = _start_isolated_agent(rt.server.url)
    try:
        node_id = _wait_store_host(rt)
        h = rt.create_actor(Writer, name="w-dying", node_id=node_id,
                            resources={"CPU": 1.0}, max_restarts=0)
        ref = h.put_table(256)
        assert rt.store_client.contains(ref)

        _kill(agent)
        deadline = time.time() + 60.0
        while time.time() < deadline:
            if not rt.store_client.contains(ref):
                break
            time.sleep(0.5)
        assert not rt.store_client.contains(ref), \
            "dead node's objects still in the table"
    finally:
        _kill(agent)


def test_engine_locality_prefers_owner_node(runtime):
    """Ref-reading tasks schedule onto an executor on the machine holding the
    refs (parity: RayDatasetRDD preferred locations). Compile-level check
    against the real location table — the pool spans two machines."""
    from raydp_tpu.etl import plan as P
    from raydp_tpu.etl.engine import Engine, ExecutorPool

    rt = runtime
    agent = _start_isolated_agent(rt.server.url)
    try:
        node_id = _wait_store_host(rt)
        w = rt.create_actor(Writer, name="w-loc", node_id=node_id,
                            resources={"CPU": 1.0})
        remote_ref = w.put_table(512)
        local_ref = rt.store_client.put(
            pa.table({"x": np.arange(512, dtype=np.int64)}))

        class _H:  # name-only handle stub; compile never submits tasks
            def __init__(self, name):
                self.name = name

        pool = ExecutorPool(
            [_H("ex-local"), _H("ex-remote")],
            hosts_by_name={"ex-local": HEAD_HOST, "ex-remote": node_id})
        engine = Engine(pool)
        schema = pa.schema([("x", pa.int64())]).serialize().to_pybytes()
        _, preferred = engine._compile(
            P.InMemory([remote_ref, local_ref], schema), temps=[])
        assert preferred == ["ex-remote", "ex-local"]

        # the reverse-conversion path reads through the same locality-routed
        # plan: to_frame emits exactly this InMemory node over the dataset's
        # refs (parity: RayDatasetRDD.getPreferredLocations over block owner
        # addresses, RayDatasetRDD.scala:48-56 — the reference's raw-bytes
        # second branch collapses into the single store here)
        from raydp_tpu.data.dataset import BlockMeta, DistributedDataset

        ds = DistributedDataset(
            [BlockMeta(num_rows=512, ref=remote_ref),
             BlockMeta(num_rows=512, ref=local_ref)],
            pa.schema([("x", pa.int64())]))

        class _Master:
            def add_objects(self, holder_id, refs):
                self.held = (holder_id, refs)

        class _Session:
            master = _Master()
            master_name = None
            engine = None

        from raydp_tpu.data.dataset import to_frame
        frame = to_frame(ds, session=_Session())
        assert isinstance(frame._plan, P.InMemory)
        assert frame._plan.refs == [remote_ref, local_ref]
        _, preferred2 = engine._compile(frame._plan, temps=[])
        assert preferred2 == ["ex-remote", "ex-local"]
    finally:
        _kill(agent)


def test_to_frame_executor_reads_node_local(runtime):
    """End-to-end reverse-conversion READ path (VERDICT r4 missing #1): a
    block written on an isolated node, wrapped by to_frame, is consumed by a
    real ETL executor actor ON that node — and the payload bytes never
    transit the head (the reference's executors likewise read RayDatasetRDD
    partitions from their node's plasma store via the partition's owner
    address, spark/dataset.py:271-291, RayDatasetRDD.scala:48-56)."""
    import cloudpickle

    from raydp_tpu.etl import plan as P
    from raydp_tpu.etl import tasks as T
    from raydp_tpu.etl.engine import Engine, ExecutorPool
    from raydp_tpu.etl.executor import EtlExecutor

    rt = runtime
    agent = _start_isolated_agent(rt.server.url)
    try:
        node_id = _wait_store_host(rt)
        w = rt.create_actor(Writer, name="w-e2e", node_id=node_id,
                            resources={"CPU": 1.0})
        ref = w.put_table(1024)
        _, _, _, _, host_id, _ = rt.store_server.lookup(ref.id)
        assert host_id == node_id

        ex = rt.create_actor(EtlExecutor, name="ex-e2e", node_id=node_id,
                             resources={"CPU": 1.0})

        # the exact task to_frame's InMemory plan compiles to, scheduled (per
        # engine._locality) onto the owner node's executor

        class _H:
            def __init__(self, name):
                self.name = name

        pool = ExecutorPool([_H("ex-e2e"), _H("ex-head")],
                            hosts_by_name={"ex-e2e": node_id,
                                           "ex-head": HEAD_HOST})
        engine = Engine(pool)
        schema = pa.schema([("x", pa.int64())]).serialize().to_pybytes()
        tasks, preferred = engine._compile(P.InMemory([ref], schema),
                                           temps=[])
        assert preferred == ["ex-e2e"]

        base = rt.store_server.payload_rpc_count
        out = ex.run_task(cloudpickle.dumps(
            tasks[0].with_output(output=T.COLLECT)))
        table = pa.ipc.open_stream(pa.py_buffer(out["ipc"])).read_all()
        assert table.num_rows == 1024
        assert table["x"][1023].as_py() == 1023
        assert rt.store_server.payload_rpc_count == base, \
            "to_frame block read transited the head instead of the node plane"
    finally:
        _kill(agent)


def test_shared_machine_agent_keeps_zero_copy_plane(runtime):
    """An agent WITHOUT isolation (same machine as the head) shares the
    head's plane: actor writes land under the head host id and reads stay
    machine-local — no RPC hops are introduced where shm works."""
    rt = runtime
    env = dict(os.environ)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("RDT_STORE_ISOLATED", None)
    agent = subprocess.Popen(
        [sys.executable, "-m", "raydp_tpu.runtime.node_agent",
         "--head", rt.server.url, "--cpus", "2.0"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        start_new_session=True)
    try:
        deadline = time.time() + 30.0
        while time.time() < deadline and not rt.node_agents:
            time.sleep(0.2)
        node_id = next(iter(rt.node_agents))
        assert node_id not in rt.store_hosts  # shared mode: no own plane
        h = rt.create_actor(Writer, name="w-shared", node_id=node_id,
                            resources={"CPU": 1.0})
        ref = h.put_table(64)
        _, _, _, _, host_id, _ = rt.store_server.lookup(ref.id)
        assert host_id == HEAD_HOST
        assert rt.store_client.get(ref).num_rows == 64
    finally:
        _kill(agent)


def test_node_hosted_spill_under_budget(runtime):
    """Node-hosted payloads past the node's shm budget LRU-spill to the
    NODE's spill dir (head directs, the bytes never leave the machine) and
    fault back in transparently when read — plasma eviction parity for the
    distributed plane, not just the head host."""
    rt = runtime
    env = dict(os.environ)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env["RDT_STORE_ISOLATED"] = "1"
    env["RDT_ARENA_FREE_GRACE_S"] = "0"
    env["RDT_NODE_ARENA_SIZE"] = str(2 << 20)   # 2 MiB node arena
    env["RDT_NODE_SHM_BUDGET"] = str(2 << 20)   # = budget
    agent = subprocess.Popen(
        [sys.executable, "-m", "raydp_tpu.runtime.node_agent",
         "--head", rt.server.url, "--cpus", "4.0"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        start_new_session=True)
    try:
        node_id = _wait_store_host(rt)
        h = rt.create_actor(Writer, name="w-spill", node_id=node_id,
                            resources={"CPU": 1.0})
        # ~50k int64 rows ≈ 0.4 MiB/object; 10 objects = 2× the 2 MiB budget
        refs = [h.put_table(50_000) for _ in range(10)]
        stats = rt.store_server.stats()
        assert stats["spilled_objects"] > 0, "nothing spilled on the node"
        with rt.store_server._lock:
            node_bytes = rt.store_server._host_bytes.get(node_id, 0)
        assert node_bytes <= (2 << 20) + 500_000, node_bytes

        # every object reads back (driver side: direct node fetch after the
        # head faults the payload back onto the node)
        for ref in refs:
            assert rt.store_client.get(ref).num_rows == 50_000
        # and the budget still holds after the reads
        with rt.store_server._lock:
            node_bytes = rt.store_server._host_bytes.get(node_id, 0)
        assert node_bytes <= (2 << 20) + 500_000, node_bytes

        rt.store_client.free(refs)
        after = rt.store_server.stats()
        assert after["spilled_bytes"] == 0
    finally:
        _kill(agent)
