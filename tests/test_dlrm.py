"""DLRM on a dp×expert mesh: embedding tables sharded, end-to-end fit
(parity target: examples/pytorch_dlrm.ipynb pipeline on Ray Train)."""

import numpy as np
import pandas as pd
import pytest


NUM_DENSE = 4
CAT_SIZES = [40, 16, 24, 8, 32, 48]  # 6 tables (downscaled Criteo shape)


def _criteo_like(session, n=2048):
    rng = np.random.RandomState(0)
    data = {"_c0": rng.randint(0, 2, n).astype(np.float64)}
    for i in range(1, NUM_DENSE + 1):
        data[f"_c{i}"] = rng.random_sample(n)
    for j, vocab in enumerate(CAT_SIZES):
        data[f"_c{NUM_DENSE + 1 + j}"] = rng.randint(0, vocab, n)
    return session.createDataFrame(pd.DataFrame(data), num_partitions=4)


def test_dlrm_model_shapes():
    import jax
    import jax.numpy as jnp

    from raydp_tpu.models import DLRM

    model = DLRM(categorical_sizes=CAT_SIZES, num_dense=NUM_DENSE,
                 embedding_dim=8, bottom_mlp=(16, 8), top_mlp=(16, 1))
    batch = {"dense": jnp.ones((32, NUM_DENSE)),
             "sparse": jnp.zeros((32, len(CAT_SIZES)), jnp.int32)}
    variables = model.init(jax.random.PRNGKey(0), batch)
    out = model.apply(variables, batch)
    assert out.shape == (32, 1)
    assert variables["params"]["embedding_0"]["embedding"].shape == (40, 8)


def test_dlrm_fit_sharded_embeddings(session):
    import optax

    from raydp_tpu.models import DLRM, criteo_batch_preprocessor, dlrm_param_rules
    from raydp_tpu.parallel import MeshSpec, make_mesh
    from raydp_tpu.train import FlaxEstimator

    mesh = make_mesh(MeshSpec(data=2, expert=4))
    df = _criteo_like(session)
    features = [f"_c{i}" for i in range(1, NUM_DENSE + 1 + len(CAT_SIZES))]

    est = FlaxEstimator(
        model=DLRM(categorical_sizes=CAT_SIZES, num_dense=NUM_DENSE,
                   embedding_dim=8, bottom_mlp=(16, 8), top_mlp=(32, 16, 1)),
        optimizer=optax.sgd(0.05),
        loss="bce_with_logits",
        feature_columns=features,
        label_column="_c0",
        feature_dtype=np.float64,
        batch_size=128,
        num_epochs=2,
        mesh=mesh,
        param_rules=dlrm_param_rules("expert"),
        batch_preprocessor=criteo_batch_preprocessor(NUM_DENSE),
        metrics=["accuracy"],
    )
    result = est.fit_on_frame(df)
    assert len(result.history) == 2
    # embedding tables actually sharded over the expert axis
    emb = result.state.params["embedding_0"]["embedding"]
    shard_rows = emb.sharding.shard_shape(emb.shape)[0]
    assert shard_rows == emb.shape[0] // 4

    # predict() works for batch_preprocessor models: the same column spec
    # decodes, the preprocessor splits, the label is read and discarded —
    # and the output matches a manual get_model() apply on the first rows
    from raydp_tpu.data import from_frame

    ds = from_frame(df)
    preds = est.predict(ds, batch_size=128)
    assert preds.shape == (2048,) and preds.dtype == np.float32

    # the normal inference frame has NO label column: predict synthesizes
    # the spec's label entry as zeros (discarded) and returns the same preds
    ds_nolabel = from_frame(df.drop("_c0"))
    np.testing.assert_array_equal(est.predict(ds_nolabel, batch_size=128),
                                  preds)

    import jax.numpy as jnp
    table = ds.get_block(0)
    feats = np.stack([table.column(c).to_numpy(zero_copy_only=False)
                      .astype(np.float64) for c in features], axis=1)
    inputs, _ = est.batch_preprocessor(
        {"features": jnp.asarray(feats),
         "label": jnp.zeros((len(feats),), jnp.float32)})
    manual = est._build_model().apply(est.get_model(), inputs)
    np.testing.assert_allclose(preds[:len(feats)],
                               np.asarray(manual).squeeze(-1),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.slow
def test_predict_synthesizes_nonstandard_label_key(session):
    """ADVICE r5 #1: a columns_spec may key its label entry anything (the
    batch_preprocessor consumes arbitrary keys) — predict() must synthesize
    zeros for ANY spec entry whose columns the inference frame lacks, not
    just the entry literally keyed "label"."""
    import optax

    from raydp_tpu.data import from_frame
    from raydp_tpu.models import MLP
    from raydp_tpu.train import FlaxEstimator

    n = 512
    rng = np.random.RandomState(0)
    pdf = pd.DataFrame({"x1": rng.rand(n), "x2": rng.rand(n),
                        "target": rng.rand(n)})
    df = session.createDataFrame(pdf, num_partitions=2)

    est = FlaxEstimator(
        model=MLP(features=(8,), use_batch_norm=False),
        optimizer=optax.adam(1e-2),
        loss="mse",
        batch_size=64,
        num_epochs=2,
        columns_spec={"features": (["x1", "x2"], np.float32),
                      "target": ("target", np.float32)},
        batch_preprocessor=lambda b: (b["features"], b["target"]),
    )
    est.fit_on_frame(df)

    preds = est.predict(from_frame(df))
    assert preds.shape == (n,) and np.isfinite(preds).all()

    # the inference frame lacks "target": the entry is synthesized as zeros
    # (its value is discarded by the preprocessor's label output anyway),
    # so predictions are identical
    preds_nolabel = est.predict(from_frame(df.drop("target")))
    np.testing.assert_array_equal(preds_nolabel, preds)

    # but a PARTIALLY-missing entry is a schema mismatch, not a label-less
    # frame: synthesizing zeros for half a feature matrix would silently
    # produce garbage predictions — it must raise instead
    with pytest.raises(ValueError, match="partially"):
        est.predict(from_frame(df.drop("x2")))
