"""ETL engine tests (parity: reference test_spark_cluster.py dataframe paths)."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from raydp_tpu.etl import functions as F
from raydp_tpu.etl.expressions import col, lit, udf, when


@pytest.fixture
def people(session):
    return session.createDataFrame(
        [{"name": "alice", "age": 30, "city": "nyc"},
         {"name": "bob", "age": 25, "city": "sf"},
         {"name": "carol", "age": 35, "city": "nyc"},
         {"name": "dave", "age": 28, "city": "sf"},
         {"name": "erin", "age": 41, "city": "nyc"}])


def test_create_and_collect(session, people):
    assert people.count() == 5
    rows = people.collect()
    assert {r["name"] for r in rows} == {"alice", "bob", "carol", "dave", "erin"}
    assert set(people.columns) == {"name", "age", "city"}


def test_select_withcolumn_filter(session, people):
    df = people.withColumn("age2", col("age") * 2).filter(col("age") > 27)
    rows = {r["name"]: r["age2"] for r in df.collect()}
    assert rows == {"alice": 60, "carol": 70, "dave": 56, "erin": 82}

    df2 = people.select("name", (col("age") + 1).alias("age_next"))
    assert set(df2.columns) == {"name", "age_next"}


def test_expressions(session, people):
    df = people.withColumn(
        "senior", when(col("age") >= 35, 1).otherwise(0)).filter(
        col("city") == "nyc")
    rows = {r["name"]: r["senior"] for r in df.collect()}
    assert rows == {"alice": 0, "carol": 1, "erin": 1}


def test_udf(session, people):
    @udf("int")
    def is_sf(city):
        return 1 if city == "sf" else 0

    df = people.withColumn("sf", is_sf("city"))
    rows = {r["name"]: r["sf"] for r in df.collect()}
    assert rows["bob"] == 1 and rows["alice"] == 0


def test_groupby_agg(session, people):
    out = people.groupBy("city").agg(
        F.mean("age").alias("avg_age"), F.count("age").alias("n")).to_pandas()
    out = out.set_index("city")
    assert out.loc["nyc", "n"] == 3
    assert abs(out.loc["nyc", "avg_age"] - (30 + 35 + 41) / 3) < 1e-9
    assert out.loc["sf", "n"] == 2


def test_join(session, people):
    cities = session.createDataFrame(
        [{"city": "nyc", "state": "NY"}, {"city": "sf", "state": "CA"}])
    joined = people.join(cities, on="city").to_pandas()
    assert len(joined) == 5
    assert set(joined.columns) >= {"name", "age", "city", "state"}
    assert (joined[joined.city == "sf"].state == "CA").all()


def test_repartition_and_coalesce(session, monkeypatch):
    # AQE's tiny-partition coalescing deliberately fuses kilobyte-sized
    # reduce buckets (doc/etl.md "Adaptive execution"), so the EXACT
    # partition count only holds with it off — rows are identical either way
    monkeypatch.setenv("RDT_ETL_AQE", "0")
    df = session.range(1000, num_partitions=2)
    rep = df.repartition(5)
    assert rep.num_partitions() == 5
    assert rep.count() == 1000
    co = rep.coalesce(2)
    assert co.num_partitions() == 2
    assert co.count() == 1000
    # with AQE on, these tiny buckets fuse into fewer dispatches — the
    # row-count contract (what repartition is FOR in a pipeline) survives
    monkeypatch.setenv("RDT_ETL_AQE", "1")
    assert 1 <= rep.num_partitions() <= 5
    assert rep.count() == 1000


def test_random_split_disjoint(session):
    df = session.range(2000, num_partitions=4)
    a, b = df.randomSplit([0.8, 0.2], seed=3)
    na, nb = a.count(), b.count()
    assert na + nb == 2000
    assert 0.7 * 2000 < na < 0.9 * 2000
    # determinism
    assert a.count() == na


def test_sort(session):
    rng = np.random.RandomState(0)
    df = session.createDataFrame(
        pd.DataFrame({"x": rng.permutation(500), "y": np.arange(500)}),
        num_partitions=4)
    out = df.sort("x").to_pandas()
    assert list(out["x"]) == sorted(out["x"])
    assert len(out) == 500


def test_sort_multikey_heavy_duplicates(session):
    """Global order with a heavily-duplicated primary key: rows tying on
    key[0] must stay contiguous and ordered by the secondary key across
    range-partition boundaries (VERDICT r2 weak #3)."""
    rng = np.random.RandomState(0)
    n = 5000
    a = rng.randint(0, 3, n)  # only 3 distinct primaries → massive ties
    b = rng.randint(0, 1000, n)
    df = session.createDataFrame(pd.DataFrame({"a": a, "b": b}),
                                 num_partitions=8)
    out = df.sort("a", "b").to_pandas().reset_index(drop=True)
    exp = pd.DataFrame({"a": a, "b": b}).sort_values(["a", "b"]) \
        .reset_index(drop=True)
    pd.testing.assert_frame_equal(out, exp)


def test_sort_nulls_land_at_end(session):
    """Null keys must land at the global end (Arrow at_end semantics), not
    in the middle where the first range bucket happens to sit — both
    directions, with a secondary key."""
    rng = np.random.RandomState(1)
    n = 3000
    a = rng.randint(0, 50, n).astype(float)
    a[rng.rand(n) < 0.15] = np.nan
    b = rng.randint(0, 100, n)
    pdf = pd.DataFrame({"a": a, "b": b})
    df = session.createDataFrame(pdf, num_partitions=6)

    out = df.sort("a", "b").to_pandas().reset_index(drop=True)
    exp = pdf.sort_values(["a", "b"], na_position="last") \
        .reset_index(drop=True)
    pd.testing.assert_frame_equal(out, exp)

    out_d = df.sort(("a", "descending"), ("b", "descending")) \
        .to_pandas().reset_index(drop=True)
    exp_d = pdf.sort_values(["a", "b"], ascending=False,
                            na_position="last").reset_index(drop=True)
    pd.testing.assert_frame_equal(out_d, exp_d)


def test_csv_roundtrip(session, tmp_path):
    rng = np.random.RandomState(1)
    pdf = pd.DataFrame({
        "a": rng.randint(0, 100, 5000),
        "b": rng.random_sample(5000),
        "s": [f"row{i}" for i in range(5000)],
    })
    path = tmp_path / "data.csv"
    pdf.to_csv(path, index=False)
    df = session.read.csv(str(path), num_partitions=4)
    assert df.num_partitions() >= 2
    assert df.count() == 5000
    got = df.to_pandas().sort_values("s").reset_index(drop=True)
    want = pdf.sort_values("s").reset_index(drop=True)
    assert (got["a"].values == want["a"].values).all()


def test_parquet_roundtrip(session, tmp_path):
    pdf = pd.DataFrame({"x": np.arange(100), "y": np.arange(100) * 1.5})
    df = session.createDataFrame(pdf, num_partitions=3)
    out_dir = str(tmp_path / "out")
    df.write.parquet(out_dir)
    back = session.read.parquet(out_dir)
    assert back.count() == 100
    assert back.to_pandas().sort_values("x")["y"].iloc[-1] == 99 * 1.5


def test_datetime_functions(session):
    pdf = pd.DataFrame({
        "ts": pd.to_datetime(["2024-01-07 13:45:00",   # a Sunday
                              "2024-06-03 02:10:00"]), # a Monday
        "v": [1.0, 2.0],
    })
    df = session.createDataFrame(pdf)
    out = df.select(
        F.hour(col("ts")).alias("h"),
        F.dayofweek(col("ts")).alias("dow"),
        F.month(col("ts")).alias("m"),
        F.year(col("ts")).alias("y"),
        F.weekofyear(col("ts")).alias("w"),
    ).to_pandas().sort_values("h").reset_index(drop=True)
    assert list(out["h"]) == [2, 13]
    # Spark semantics: Sunday=1, Monday=2
    assert list(out["dow"]) == [2, 1]
    assert list(out["m"]) == [6, 1]


def test_persist_and_release(session):
    df = session.range(1000, num_partitions=4).withColumn(
        "sq", col("id") * col("id"))
    cached = df.persist()
    assert cached.count() == 1000
    frame_id = cached._plan.frame_id
    assert frame_id in session.cached_frames()
    # blocks live on executors
    keys = set()
    for h in session.executors:
        keys.update(h.list_blocks())
    assert any(k.startswith(f"block_{frame_id}_") for k in keys)
    cached.unpersist()
    assert frame_id not in session.cached_frames()


def test_block_recovery_after_executor_crash(session):
    """Kill an executor holding cached blocks; lineage recomputes on fetch.

    Parity: the recoverable-dataset fault test (test_spark_cluster.py:262-299)
    and the recache protocol (RayDPExecutor.scala:312-355)."""
    import time

    df = session.range(400, num_partitions=4).withColumn("sq", col("id") * 2)
    cached = df.persist()
    plan = cached._plan
    # crash (not deliberate-kill) every executor: caches are wiped
    for h in session.executors:
        try:
            h.call("crash")
        except Exception:
            pass

    def try_count():
        return cached.count()

    deadline = time.time() + 60
    value = None
    while time.time() < deadline:
        try:
            value = try_count()
            break
        except Exception:
            time.sleep(0.5)
    assert value == 400


def test_dropna_fillna(session):
    df = session.createDataFrame(pd.DataFrame({
        "a": [1.0, None, 3.0, None], "b": ["x", "y", None, "w"]}))
    assert df.dropna().count() == 1
    assert df.dropna(subset=["a"]).count() == 2
    filled = df.fillna(0.0, subset=["a"]).to_pandas()
    assert filled["a"].isna().sum() == 0


def test_global_limit(session):
    # regression: limit() must be global, not per-partition
    df = session.range(1000, num_partitions=4)
    assert df.limit(5).count() == 5
    assert len(df.limit(5).collect()) == 5
    assert df.limit(5000).count() == 1000


def test_sort_string_column(session):
    # regression: orderBy on non-numeric keys (no float cast)
    import pandas as pd
    pdf = pd.DataFrame({"s": [f"key{i:04d}" for i in range(300)][::-1],
                        "v": range(300)})
    df = session.createDataFrame(pdf, num_partitions=3)
    out = df.sort("s").to_pandas()
    assert list(out["s"]) == sorted(out["s"])


def test_join_then_sort(session):
    # regression: a Sort nested beside another shuffle must not free the
    # sibling shuffle's intermediates mid-plan
    left = session.createDataFrame(
        [{"k": i % 5, "a": i} for i in range(100)], num_partitions=2)
    right = session.createDataFrame(
        [{"k": k, "b": k * 10} for k in range(5)], num_partitions=2)
    out = left.join(right.sort("k"), on="k").to_pandas()
    assert len(out) == 100


def test_modulo_semantics(session):
    import pandas as pd

    from raydp_tpu.etl.expressions import col
    big = 9_007_199_254_740_995  # > 2^53: float64 round-trip would corrupt
    df = session.createDataFrame(pd.DataFrame({
        "x": [10, -7, big, 5], "y": [3, 3, 1000, 0]}))
    rows = df.withColumn("m", col("x") % col("y")).to_pandas()
    m = {int(x): v for x, v in zip(rows["x"], rows["m"])}
    assert m[10] == 1
    assert m[-7] == 2  # Python semantics
    assert m[big] == big % 1000
    import math
    assert rows["m"].isna().iloc[3] or math.isnan(rows["m"].iloc[3])  # div by 0 -> null


def test_sort_sorted_input_balanced_ranges(session):
    # regression (sort sampling skew): already-sorted input used to have its
    # boundaries sampled from the first blocks only, collapsing every row
    # into one range partition
    df = session.createDataFrame(
        pd.DataFrame({"x": np.arange(2000)}), num_partitions=4)
    out = df.sort("x").to_pandas()
    assert list(out["x"]) == list(range(2000))


def test_concurrent_actions(session, people):
    # two shuffling actions racing on one session must not cross-free each
    # other's shuffle intermediates (Engine tracks temps per action)
    import threading

    errors = []
    results = {}

    def _agg(tag):
        try:
            out = people.groupBy("city").agg(
                F.count("age").alias("n")).to_pandas().set_index("city")
            results[tag] = int(out.loc["nyc", "n"])
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=_agg, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    assert all(v == 3 for v in results.values())


def test_dynamic_allocation_shrink_grow(session):
    from raydp_tpu.data.dataset import from_frame_recoverable

    pdf = pd.DataFrame({"x": np.arange(4000), "y": np.arange(4000) % 7})
    df = session.createDataFrame(pdf, num_partitions=4)
    ds = from_frame_recoverable(df, fetch=False)  # cached across 2 executors

    # shrink: the killed executor's cached blocks must recover via lineage
    # on the survivor (parity: RayCoarseGrainedSchedulerBackend.scala:278-301)
    assert session.request_total_executors(1) == 1
    total = sum(ds.get_block(i).num_rows for i in range(ds.num_blocks()))
    assert total == 4000

    # grow back up; new executors serve fresh work
    assert session.request_total_executors(3) == 3
    df2 = session.createDataFrame(pdf, num_partitions=6)
    assert df2.count() == 4000
    out = df2.groupBy("y").agg(F.count("x").alias("n")).to_pandas()
    assert int(out["n"].sum()) == 4000


def test_distinct_and_drop_duplicates(session):
    """distinct/dropDuplicates parity (reference examples/data_process.py):
    executor-side hash-shuffle dedupe, exact global result."""
    pdf = pd.DataFrame({
        "a": [1, 1, 2, 2, 3] * 40,
        "b": ["x", "x", "y", "z", "x"] * 40,
    })
    df = session.createDataFrame(pdf, num_partitions=4)
    out = df.distinct().to_pandas().sort_values(["a", "b"]).reset_index(drop=True)
    exp = pdf.drop_duplicates().sort_values(["a", "b"]).reset_index(drop=True)
    pd.testing.assert_frame_equal(out, exp)

    # subset dedupe keeps one full row per key value
    by_a = df.dropDuplicates(["a"]).to_pandas()
    assert sorted(by_a["a"]) == [1, 2, 3]
    assert set(by_a.columns) == {"a", "b"}

    # dedupe after a transform, with nulls (null is a distinct value)
    pdf2 = pd.DataFrame({"k": [1.0, None, 1.0, None, 2.0]})
    df2 = session.createDataFrame(pdf2, num_partitions=2)
    assert df2.distinct().count() == 3


def test_describe(session):
    rng = np.random.RandomState(7)
    pdf = pd.DataFrame({"x": rng.normal(10, 3, 2000),
                        "y": rng.randint(0, 5, 2000),
                        "s": ["t"] * 2000})
    df = session.createDataFrame(pdf, num_partitions=4)
    out = df.describe().to_pandas().set_index("summary")
    assert "s" not in out.columns  # non-numeric skipped
    assert out.loc["count", "x"] == 2000
    np.testing.assert_allclose(out.loc["mean", "x"], pdf["x"].mean(), rtol=1e-9)
    np.testing.assert_allclose(out.loc["stddev", "x"], pdf["x"].std(ddof=1),
                               rtol=1e-9)
    assert out.loc["min", "y"] == pdf["y"].min()
    assert out.loc["max", "y"] == pdf["y"].max()
    # explicit column selection
    one = df.describe("y").to_pandas()
    assert list(one.columns) == ["summary", "y"]


def test_sort_mixed_directions(session):
    """Composite-key range sort with per-key direction mix: ascending primary,
    descending secondary — the boundary comparison must honor each key's
    direction (single-key bucketing reversed globally and broke this)."""
    rng = np.random.RandomState(3)
    n = 3000
    a = rng.randint(0, 4, n)
    b = rng.randint(0, 500, n)
    df = session.createDataFrame(pd.DataFrame({"a": a, "b": b}),
                                 num_partitions=6)
    out = df.sort(("a", "ascending"), ("b", "descending")) \
        .to_pandas().reset_index(drop=True)
    exp = pd.DataFrame({"a": a, "b": b}).sort_values(
        ["a", "b"], ascending=[True, False]).reset_index(drop=True)
    pd.testing.assert_frame_equal(out, exp)


def test_sort_low_cardinality_primary_balanced(session):
    """With 2 distinct primary values, composite boundaries must still spread
    rows over >2 range partitions (single-key boundaries collapse to 1)."""
    rng = np.random.RandomState(5)
    n = 4000
    pdf = pd.DataFrame({"a": rng.randint(0, 2, n), "b": rng.permutation(n)})
    df = session.createDataFrame(pdf, num_partitions=8)
    sorted_df = df.sort("a", "b")
    assert sorted_df.num_partitions() > 2
    out = sorted_df.to_pandas().reset_index(drop=True)
    exp = pdf.sort_values(["a", "b"]).reset_index(drop=True)
    pd.testing.assert_frame_equal(out, exp)


def test_sort_float_with_nans(session):
    """NaN sort keys must land at the global end (Arrow orders NaN above all
    numbers), not in the first range partition (code-review r4 finding)."""
    rng = np.random.RandomState(11)
    vals = rng.rand(2000) * 100
    vals[rng.choice(2000, 25, replace=False)] = np.nan
    df = session.createDataFrame(pd.DataFrame({"x": vals}), num_partitions=6)
    out = df.sort("x").to_pandas()["x"].to_numpy()
    finite = out[~np.isnan(out)]
    assert len(finite) == 2000 - 25
    assert (np.diff(finite) >= 0).all()
    assert np.isnan(out[-25:]).all()  # NaNs contiguous at the end
