"""Logical-plan optimizer: equivalence vs the naive path + rule unit tests.

The contract: for ANY plan, ``collect()`` under ``RDT_ETL_OPTIMIZER=1`` must
equal ``=0`` row-for-row (after a canonical sort — bucket concat order is not
part of the result), and the engine's shuffled-byte counters must strictly
drop where a rule should fire."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from raydp_tpu.etl import functions as F
from raydp_tpu.etl import optimizer as O
from raydp_tpu.etl import plan as P
from raydp_tpu.etl import tasks as T
from raydp_tpu.etl.expressions import col, substitute_columns


@pytest.fixture(scope="module")
def session():
    """Module-scoped session override: these ~12 tests share one 2-executor
    gang instead of paying ~9s of bring-up each — the tier-1 870s window is
    a shared budget, and plans/frames are immutable so reuse is safe."""
    import raydp_tpu

    s = raydp_tpu.init("pytest_opt", num_executors=2, executor_cores=1,
                       executor_memory="512MB")
    yield s
    raydp_tpu.stop()


@pytest.fixture(scope="module")
def wide(session):
    """Null-heavy wide frame: key + 6 columns, several dtypes."""
    rng = np.random.RandomState(0)
    n = 2000
    pdf = pd.DataFrame({
        "k": rng.randint(0, 9, n),
        "a": rng.randint(0, 1000, n).astype(np.int64),
        "b": rng.random_sample(n),
        "s": [f"tag{i % 13}" for i in range(n)],
        "c": rng.randint(0, 50, n).astype(float),
        "d": rng.randint(0, 7, n),
        "e": rng.random_sample(n),
    })
    pdf.loc[rng.rand(n) < 0.15, "b"] = np.nan
    pdf.loc[rng.rand(n) < 0.1, "c"] = np.nan
    return session.createDataFrame(pdf, num_partitions=4)


def both_paths(monkeypatch, session, make_df, sort_cols, approx=False):
    """collect() under optimizer off and on; assert equal; return reports."""
    outs, reports = {}, {}
    for env in ("0", "1"):
        monkeypatch.setenv("RDT_ETL_OPTIMIZER", env)
        session.engine.reset_shuffle_stage_report()
        outs[env] = (make_df().to_pandas().sort_values(sort_cols)
                     .reset_index(drop=True))
        reports[env] = session.engine.shuffle_stage_report()
    monkeypatch.delenv("RDT_ETL_OPTIMIZER", raising=False)
    if approx:
        pd.testing.assert_frame_equal(outs["0"], outs["1"], check_exact=False)
    else:
        pd.testing.assert_frame_equal(outs["0"], outs["1"])
    return outs["1"], reports


def _bytes(report):
    return sum(r["bytes_shuffled"] for r in report)


# ==== equivalence matrix ===========================================================
def test_groupagg_matrix_equivalent_and_fewer_bytes(monkeypatch, session, wide):
    out, reports = both_paths(
        monkeypatch, session,
        lambda: wide.groupBy("k").agg(
            F.sum("a").alias("sa"), F.mean("b").alias("mb"),
            F.count("a").alias("n"), F.min("c").alias("mn"),
            F.max("a").alias("mx")),
        ["k"], approx=True)
    assert len(out) == 9
    # partial aggregation + pruning must strictly shrink the shuffle
    assert _bytes(reports["1"]) < _bytes(reports["0"])
    assert [r["stage"] for r in reports["1"]] == ["groupagg-partial"]
    assert (sum(r["rows_shuffled"] for r in reports["1"])
            < sum(r["rows_shuffled"] for r in reports["0"]))
    # the in/out split shows the map-side reduction: every input row enters
    # the stage, roughly keys×maps partial rows leave it
    stage = reports["1"][0]
    assert stage["rows_in"] == 2000
    assert stage["rows_shuffled"] < stage["rows_in"]
    assert 0 < stage["bytes_shuffled"] < stage["bytes_in"]


def test_groupagg_nondecomposable_falls_back(monkeypatch, session, wide):
    out, reports = both_paths(
        monkeypatch, session,
        lambda: wide.groupBy("k").agg(F.stddev("a").alias("sd"),
                                      F.count_distinct("d").alias("cd")),
        ["k"], approx=True)
    assert [r["stage"] for r in reports["1"]] == ["groupagg"]
    # projection pruning still narrows the shuffle even without partials
    assert _bytes(reports["1"]) < _bytes(reports["0"])


def test_join_projected_equivalent_and_fewer_bytes(monkeypatch, session, wide):
    dim = session.createDataFrame(
        pd.DataFrame({"k": np.arange(9), "label": [f"L{i}" for i in range(9)],
                      "extra": np.arange(9) * 2.0}),
        num_partitions=2)
    out, reports = both_paths(
        monkeypatch, session,
        lambda: wide.join(dim, on="k").select("k", "a", "label"),
        ["k", "a"])
    assert set(out.columns) == {"k", "a", "label"}
    assert _bytes(reports["1"]) < _bytes(reports["0"])


def test_filter_pushdown_through_project_rename_union(monkeypatch, session,
                                                      wide):
    def make():
        u = wide.select("k", "a").union(wide.select("k", "a"))
        return (u.withColumnRenamed("a", "aa")
                .filter(col("aa") % 3 == 0)
                .filter(col("k") > 2))

    out, _ = both_paths(monkeypatch, session, make, ["k", "aa"])
    assert (out["aa"] % 3 == 0).all() and (out["k"] > 2).all()


def test_window_then_groupby_composition(monkeypatch, session, wide):
    from raydp_tpu.etl.window import Window

    w = Window.partitionBy("k").orderBy("a")

    def make():
        return (wide.withColumn("rn", F.row_number().over(w))
                .filter(col("rn") <= 5)
                .groupBy("k").agg(F.sum("a").alias("sa"),
                                  F.count("rn").alias("n")))

    out, _ = both_paths(monkeypatch, session, make, ["k"])
    assert (out["n"] <= 5).all()


def test_groupagg_high_cardinality_rowwise_partials(monkeypatch, session):
    """Near-unique keys: the sampled guard must emit row-wise partials (no
    per-map hash pass, rows shuffled == rows in) and still merge exactly —
    the committed bench recorded +47% wall on 100k-cardinality keys when
    partials were grouped unconditionally."""
    rng = np.random.RandomState(4)
    n = 3000
    pdf = pd.DataFrame({"k": rng.permutation(n),
                        "v": rng.randint(0, 100, n).astype(np.int64),
                        "f": rng.randint(0, 9, n).astype(float)})
    df = session.createDataFrame(pdf, num_partitions=3)
    out, reports = both_paths(
        monkeypatch, session,
        lambda: df.groupBy("k").agg(F.sum("v").alias("s"),
                                    F.mean("f").alias("m"),
                                    F.count("v").alias("n")),
        ["k"])
    assert len(out) == n and (out["n"] == 1).all()
    stage = reports["1"][0]
    assert stage["stage"] == "groupagg-partial"
    # unique keys: nothing to collapse, so the guard passes rows through 1:1
    assert stage["rows_shuffled"] == stage["rows_in"] == n


def test_rowwise_partials_match_grouped_partials():
    """The two partial representations must merge to the same result: a raw
    row is a group of size 1 (types widened identically via the probe)."""
    t = pa.table({"k": list(range(6)),
                  "v": pa.array([1, None, 3, 4, None, 6], pa.int32()),
                  "b": [True, False, None, True, True, False]})
    partials, merges = T.decompose_aggs(
        [("v", "sum", "s"), ("v", "mean", "m"), ("v", "count", "n"),
         ("b", "sum", "bs")])
    step = T.GroupAggPartialStep(["k"], partials)
    grouped = step.run(t)            # 6 rows < 256 → grouped path
    rowwise = step._rowwise(t)
    merge = T.GroupAggMergeStep(["k"], merges)
    a = merge.run(grouped).sort_by("k")
    b = merge.run(rowwise).sort_by("k")
    assert a.equals(b), (a.to_pylist(), b.to_pylist())


def test_distinct_and_limit_composition(monkeypatch, session, wide):
    out, _ = both_paths(
        monkeypatch, session,
        lambda: wide.select("k", "d").distinct(),
        ["k", "d"])
    assert len(out) == len(out.drop_duplicates())

    both_paths(monkeypatch, session,
               lambda: wide.select("k", "a").limit(7), ["k", "a"])


def test_sort_with_pruned_payload(monkeypatch, session, wide):
    both_paths(monkeypatch, session,
               lambda: wide.select("k", "a", "b").sort(
                   "k", ("a", "descending")),
               ["k", "a"], approx=True)


def test_null_heavy_mean_sum_count(monkeypatch, session):
    pdf = pd.DataFrame({
        "k": [1, 1, 2, 2, 3, 3] * 50,
        "v": ([None, None, 1.0, None, 2.0, 3.0] * 50),
    })
    df = session.createDataFrame(pdf, num_partitions=3)
    # approx: float partials sum in a different order than one-pass
    # aggregation, so the last ulp may differ (bit-identity holds for ints)
    out, _ = both_paths(
        monkeypatch, session,
        lambda: df.groupBy("k").agg(F.mean("v").alias("m"),
                                    F.sum("v").alias("s"),
                                    F.count("v").alias("n")),
        ["k"], approx=True)
    row = out.set_index("k")
    assert pd.isna(row.loc[1, "m"]) and row.loc[1, "n"] == 0
    assert row.loc[2, "m"] == 1.0 and row.loc[2, "n"] == 50  # nulls skipped
    assert row.loc[3, "m"] == 2.5 and row.loc[3, "n"] == 100


def test_filter_does_not_commute_with_sample(monkeypatch, session, wide):
    """Sample draws are positional: sinking a filter below sample would pick
    a DIFFERENT random row set. The optimizer must keep the filter above."""
    out, _ = both_paths(
        monkeypatch, session,
        lambda: wide.sample(0.5, seed=11).filter(col("k") > 4)
                    .select("k", "a"),
        ["k", "a"])
    assert (out["k"] > 4).all()
    a, b = wide.randomSplit([0.5, 0.5], seed=5)
    both_paths(monkeypatch, session,
               lambda: a.filter(col("d") < 3).select("k", "d"), ["k", "d"])


def test_filter_stack_order_preserved_guard_predicate(monkeypatch, session):
    """An earlier filter may GUARD a later one (b != 0 before a/b): Arrow
    kernels raise eagerly instead of yielding null, so the optimizer must
    never reorder stacked filters (code-review finding: the leapfrogged
    divide crashed with ArrowInvalid where the naive path returned rows)."""
    df = session.createDataFrame(
        pd.DataFrame({"a": [10, 20, 30, 40], "b": [0, 2, 0, 4]}),
        num_partitions=2)
    out, _ = both_paths(
        monkeypatch, session,
        lambda: df.filter(col("b") != 0).filter((col("a") / col("b")) > 6),
        ["a", "b"])
    assert out["a"].tolist() == [20, 40]


def test_hash_buckets_nested_column_falls_back():
    """Non-dictionary-encodable key columns (nested types) must take the
    per-row fallback, not crash (code-review finding: dead except clause)."""
    t = pa.table({"k": pa.array([[1, 2], [1, 2], [3]]), "v": [1, 2, 3]})
    buckets = T.hash_buckets(t, ["k"], 4)
    assert sum(b.num_rows for b in buckets) == 3
    # equal nested keys land in the same bucket
    homes = [i for i, b in enumerate(buckets)
             if [1, 2] in b.column("k").to_pylist()]
    assert len(homes) == 1


def test_window_chain_stays_one_shuffle(monkeypatch, session, wide):
    from raydp_tpu.etl.window import Window

    w = Window.partitionBy("k").orderBy("a")

    def make():
        return (wide.withColumn("rn", F.row_number().over(w))
                .withColumn("prev", F.lag("a", 1, -1).over(w))
                .select("k", "a", "rn", "prev"))

    out, reports = both_paths(monkeypatch, session, make, ["k", "a"])
    # same-spec windows collapse into ONE shuffle on both paths — a prune
    # Project inserted between them would split the chain
    assert [r["stage"] for r in reports["1"]] == ["window"]
    assert [r["stage"] for r in reports["0"]] == ["window"]
    assert _bytes(reports["1"]) < _bytes(reports["0"])


# ==== satellite regressions ========================================================
def test_negative_zero_groupby_single_key_row(monkeypatch, session):
    df = session.createDataFrame(
        pd.DataFrame({"k": [0.0, -0.0, 1.0, -0.0, 0.0],
                      "v": [1, 2, 3, 4, 5]}), num_partitions=2)
    for env in ("0", "1"):
        monkeypatch.setenv("RDT_ETL_OPTIMIZER", env)
        out = df.groupBy("k").agg(F.sum("v").alias("sv")).to_pandas()
        assert len(out) == 2, out
        assert sorted(out["sv"]) == [3, 12]
    assert df.dropDuplicates(["k"]).count() == 2


def test_negative_zero_hash_buckets_agree():
    t = pa.table({"k": pa.array([0.0, -0.0], pa.float64())})
    buckets = T.hash_buckets(t, ["k"], 16)
    nonempty = [i for i, b in enumerate(buckets) if b.num_rows]
    assert len(nonempty) == 1 and buckets[nonempty[0]].num_rows == 2


def test_string_and_dictionary_keys_hash_equal():
    strings = pa.array(["x", "y", None, "x", "z"])
    plain = pa.table({"k": strings, "v": [1, 2, 3, 4, 5]})
    as_dict = pa.table({"k": strings.dictionary_encode(),
                        "v": [1, 2, 3, 4, 5]})
    nb = 8
    for b_plain, b_dict in zip(T.hash_buckets(plain, ["k"], nb),
                               T.hash_buckets(as_dict, ["k"], nb)):
        assert b_plain.column("v").to_pylist() == \
            b_dict.column("v").to_pylist()


def test_single_pass_bucketing_matches_filter_loop():
    rng = np.random.RandomState(2)
    t = pa.table({"k": rng.randint(0, 100, 500), "v": np.arange(500)})
    bucket = np.asarray(t.column("k")) % 7
    got = T.split_by_bucket(t, bucket.astype(np.int64), 7)
    for b in range(7):
        expect = t.filter(pa.array(bucket == b))
        assert got[b].equals(expect)
    assert sum(g.num_rows for g in got) == 500


def test_round_robin_and_random_buckets_exhaustive():
    t = pa.table({"v": np.arange(101)})
    rr = T.round_robin_buckets(t, 4, start=2)
    assert sum(b.num_rows for b in rr) == 101
    assert pa.concat_tables(rr).sort_by("v").equals(t)
    rb = T.random_buckets(t, 4, seed=9)
    assert sum(b.num_rows for b in rb) == 101
    assert pa.concat_tables(rb).sort_by("v").equals(t)
    # determinism: a recomputed map task lands rows identically
    rb2 = T.random_buckets(t, 4, seed=9)
    for x, y in zip(rb, rb2):
        assert x.equals(y)


# ==== optimizer rule unit tests (pure plan level) ==================================
def test_references_walks_expression_trees():
    from raydp_tpu.etl.expressions import when
    e = (col("a") + col("b") * 2).alias("x")
    assert e.references() == {"a", "b"}
    w = when(col("p") > 0, col("q")).otherwise(col("r"))
    assert w.references() == {"p", "q", "r"}
    assert substitute_columns(w, {"p": "pp"}).references() == {"pp", "q", "r"}


def test_prune_pushes_columns_into_parquet_scan():
    scan = P.ParquetScan(["f.parquet"])
    plan = P.GroupAgg(scan, ["k"], [("v", "sum", "sv")])
    opt = O.prune_columns(plan, None)
    assert isinstance(opt.child, P.ParquetScan)
    assert opt.child.columns == ["k", "v"]


def test_prune_inserts_post_read_project_for_csv():
    scan = P.CsvScan(["f.csv"])
    plan = P.GroupAgg(scan, ["k"], [("v", "sum", "sv")])
    opt = O.prune_columns(plan, None)
    assert isinstance(opt.child, P.Project)
    assert [n for n, _ in opt.child.columns] == ["k", "v"]


def test_filter_sinks_below_rename_with_rewritten_names():
    plan = P.Filter(P.Rename(P.CsvScan(["f.csv"]), {"old": "new"}),
                    col("new") > 3)
    opt = O.push_filters(plan)
    assert isinstance(opt, P.Rename)
    assert isinstance(opt.child, P.Filter)
    assert opt.child.predicate.references() == {"old"}


def test_filter_sinks_below_union_and_passthrough_project():
    proj = P.Project(P.CsvScan(["f.csv"]), [("k", col("k")), ("v", col("v"))])
    plan = P.Filter(P.Union([proj, proj]), col("k") > 0)
    opt = O.push_filters(plan)
    assert isinstance(opt, P.Union)
    for inp in opt.inputs:
        assert isinstance(inp, P.Project)
        assert isinstance(inp.child, P.Filter)


def test_filter_stays_above_computed_projection():
    proj = P.Project(P.CsvScan(["f.csv"]), [("x", col("a") + 1)])
    plan = P.Filter(proj, col("x") > 0)
    opt = O.push_filters(plan)
    assert isinstance(opt, P.Filter)  # cannot sink below the computation


def test_optimizer_disabled_is_identity(monkeypatch):
    monkeypatch.setenv("RDT_ETL_OPTIMIZER", "0")
    plan = P.GroupAgg(P.ParquetScan(["f.parquet"]), ["k"],
                      [("v", "sum", "sv")])
    assert O.optimize(plan) is plan
    monkeypatch.setenv("RDT_ETL_OPTIMIZER", "1")
    assert O.optimize(plan) is not plan


def test_decompose_aggs_shares_partials():
    partials, merges = T.decompose_aggs(
        [("v", "mean", "m"), ("v", "sum", "s"), ("v", "count", "n"),
         ("w", "min", "lo")])
    # mean shares its sum partial with sum() and its count with count()
    assert len(partials) == 3
    kinds = {out: kind for out, kind, _ in merges}
    assert kinds == {"m": "mean", "s": "sum", "n": "sum", "lo": "min"}
    with pytest.raises(ValueError):
        T.decompose_aggs([("v", "stddev", "x")])
