"""Fault-injection plane unit tests: spec grammar, deterministic schedules,
the cross-process once-sentinel, and the store/rpc injection points (the
executor-side points are exercised end to end by tests/test_chaos.py)."""

import pytest

from raydp_tpu import faults


def test_parse_spec_grammar(tmp_path):
    rules = faults.parse_spec(
        "executor.run_task:crash:nth=3:once=/tmp/s;"
        "store.get:drop:p=0.25:seed=7:match=abc;"
        "rpc.call:delay:ms=5:every=2:times=3", default_seed=42)
    assert [r.site for r in rules] == ["executor.run_task", "store.get",
                                       "rpc.call"]
    crash, drop, delay = rules
    assert crash.action == "crash" and crash.nth == 3 and crash.once == "/tmp/s"
    assert crash.seed == 42  # default seed rides along
    assert drop.p == 0.25 and drop.seed == 7 and drop.match == "abc"
    assert delay.ms == 5.0 and delay.every == 2 and delay.times == 3

    with pytest.raises(ValueError):
        faults.parse_spec("just-a-site")
    with pytest.raises(ValueError):
        faults.parse_spec("store.get:raise:bogus_option=1")
    with pytest.raises(ValueError):
        faults.parse_spec("store.get:raise:notkeyvalue")
    # a typo'd or misplaced action must fail the parse, not silently arm a
    # rule that claims its once-sentinel while injecting nothing
    with pytest.raises(ValueError):
        faults.parse_spec("executor.run_task:dorp:nth=1")
    # same loud-failure contract for a typo'd SITE: the env spec names an
    # injection point that exists nowhere in code (faults.KNOWN_SITES)
    with pytest.raises(ValueError):
        # rdtlint: allow[fault-site-sync] deliberately typo'd site
        faults.parse_spec("executor.run_tsak:crash:nth=1")
    with pytest.raises(ValueError):
        faults.parse_spec("rpc.call:drop:nth=1")
    with pytest.raises(ValueError):
        faults.parse_spec("store.get:connloss:nth=1")


def test_nth_schedule_fires_exactly_once():
    rule = faults.FaultRule("s", "raise", nth=3)
    assert [rule.should_fire("k") for _ in range(6)] == \
        [False, False, True, False, False, False]


def test_every_and_times_schedules():
    rule = faults.FaultRule("s", "raise", every=2, times=2)
    fired = [rule.should_fire("k") for _ in range(8)]
    assert fired == [False, True, False, True, False, False, False, False]


def test_probability_schedule_is_seed_deterministic():
    a = faults.FaultRule("s", "raise", p=0.5, seed=11)
    b = faults.FaultRule("s", "raise", p=0.5, seed=11)
    pattern_a = [a.should_fire("k") for _ in range(64)]
    pattern_b = [b.should_fire("k") for _ in range(64)]
    assert pattern_a == pattern_b
    assert any(pattern_a) and not all(pattern_a)
    c = faults.FaultRule("s", "raise", p=0.5, seed=12)
    assert [c.should_fire("k") for _ in range(64)] != pattern_a


def test_stacked_identical_p_rules_draw_independent_streams():
    """Two spec rules identical in (seed, site, action) must not mirror each
    other's p= draws — the registry index feeds the PRNG stream."""
    a, b = faults.parse_spec("store.get:raise:p=0.5;store.get:raise:p=0.5",
                             default_seed=3)
    pattern_a = [a.should_fire("k") for _ in range(64)]
    pattern_b = [b.should_fire("k") for _ in range(64)]
    assert pattern_a != pattern_b


def test_match_filter_does_not_consume_calls():
    rule = faults.FaultRule("s", "raise", nth=1, match="hot")
    assert rule.should_fire("cold") is False
    assert rule.calls == 0  # non-matching keys don't advance the schedule
    assert rule.should_fire("hotpath") is True


def test_once_sentinel_single_winner(tmp_path):
    path = str(tmp_path / "sentinel")
    # two rules with the same sentinel model the same env spec loaded by two
    # processes: exactly one fire wins
    a = faults.FaultRule("s", "crash", nth=1, once=path)
    b = faults.FaultRule("s", "crash", nth=1, once=path)
    assert a.should_fire("k") is True
    assert b.should_fire("k") is False
    assert (tmp_path / "sentinel").exists()


def test_registry_check_and_clear():
    faults.clear()
    try:
        rule = faults.inject("unit.site", "raise", nth=2)
        assert faults.check("unit.site", "k") is None
        got = faults.check("unit.site", "k")
        assert got is rule
        assert faults.check("other.site", "k") is None
        assert rule.fires == 1
    finally:
        faults.clear()
    assert faults.check("unit.site", "k") is None


def test_reset_keeps_programmatic_rules(monkeypatch):
    """init() calls reset() to re-arm from the current env; a rule armed via
    inject() BEFORE init must survive it — silently disarming would make the
    chaos run test nothing — while env rules are reloaded fresh."""
    faults.clear()
    try:
        rule = faults.inject("unit.site", "raise", nth=1)
        monkeypatch.setenv("RDT_FAULTS", "rpc.call:delay:ms=1")
        faults.reset()
        armed = faults.rules()
        assert rule in armed, "inject()-ed rule lost across reset()"
        assert any(r.site == "rpc.call" for r in armed), \
            "env spec not re-armed by reset()"
    finally:
        faults.clear()


def test_env_rules_reloaded_after_reset_get_fresh_indices(monkeypatch):
    """An env rule reloaded after reset() must not reuse a surviving
    inject()-ed rule's PRNG index: identical (seed, site, action) pairs
    would mirror each other's p= draws, collapsing the intended doubled
    schedule into one."""
    faults.clear()
    try:
        kept = faults.inject("store.get", "drop", p=0.5, seed=3)
        monkeypatch.setenv("RDT_FAULTS", "store.get:drop:p=0.5")
        monkeypatch.setenv("RDT_FAULTS_SEED", "3")
        faults.reset()
        armed = faults.rules()
        env_rule = next(r for r in armed if r is not kept)
        assert env_rule.index != kept.index
        # fresh copies (rules() shares state): streams must differ
        a = faults.FaultRule("store.get", "drop", p=0.5, seed=3,
                             index=kept.index)
        b = faults.FaultRule("store.get", "drop", p=0.5, seed=3,
                             index=env_rule.index)
        assert [a.should_fire("k") for _ in range(64)] != \
            [b.should_fire("k") for _ in range(64)]
        # and a rule inject()-ed after the reload keeps the invariant too
        late = faults.inject("store.get", "drop", p=0.5, seed=3)
        assert len({r.index for r in (kept, env_rule, late)}) == 3
    finally:
        faults.clear()


def test_apply_delay_and_raise():
    import time
    rule = faults.FaultRule("s", "delay", ms=30)
    t0 = time.monotonic()
    faults.apply(rule, "s")
    assert time.monotonic() - t0 >= 0.025
    with pytest.raises(faults.InjectedFault):
        faults.apply(faults.FaultRule("s", "raise"), "s")


def test_apply_delay_scales_with_reported_bytes():
    """``ms_per_mb=`` scales a delay by the payload a data-plane site
    reports (the slow-data-plane model the AQE skew bench uses): 2 MiB at
    20ms/MiB ≈ 40ms on top of a zero fixed delay; a site that reports no
    bytes pays only the fixed part."""
    import time
    rule = faults.parse_spec("shuffle.fetch:delay:ms=0:ms_per_mb=20")[0]
    t0 = time.monotonic()
    faults.apply(rule, "shuffle.fetch", nbytes=2 << 20)
    assert time.monotonic() - t0 >= 0.035
    t0 = time.monotonic()
    faults.apply(rule, "shuffle.fetch")          # no bytes → no scaled part
    assert time.monotonic() - t0 < 0.03


def test_shuffle_fetch_drop_site_is_valid_and_store_sites_reject_it():
    # shuffle.fetch interprets drop (the ranged-read loss model); arming a
    # drop at rpc.call must still fail loudly
    rule = faults.parse_spec("shuffle.fetch:drop:nth=1")[0]
    assert rule.site == "shuffle.fetch" and rule.action == "drop"
    with pytest.raises(ValueError):
        faults.parse_spec("rpc.call:drop:nth=1")


def test_store_get_drop_raises_object_lost(runtime):
    """The store.get injection point: a dropped blob raises the typed
    ObjectLostError AND is genuinely gone for every later reader."""
    from raydp_tpu.runtime.object_store import ObjectLostError

    client = runtime.store_client
    ref = client.put({"x": 1})
    faults.clear()
    try:
        faults.inject("store.get", "drop", match=ref.id, times=1)
        with pytest.raises(ObjectLostError) as ei:
            client.get(ref)
        assert ref.id in str(ei.value)
        assert ei.value.object_id == ref.id
        # blob truly removed: the next read misses WITHOUT the fault firing
        assert not client.contains(ref)
        with pytest.raises(ObjectLostError):
            client.get(ref)
    finally:
        faults.clear()


def test_free_then_get_raises_object_lost(runtime):
    """Even without injection, a read of a freed/lost blob surfaces as the
    typed signal (what the engine keys lineage recovery on), not a bare
    KeyError."""
    from raydp_tpu.runtime.object_store import ObjectLostError

    client = runtime.store_client
    ref = client.put(b"payload")
    client.free([ref])
    with pytest.raises(ObjectLostError):
        client.get(ref)
    # still a KeyError subclass, so pre-existing broad handlers keep working
    assert issubclass(ObjectLostError, KeyError)


def test_rpc_connloss_is_absorbed_by_handle_retry(runtime):
    """The rpc.call injection point: one injected connection loss on an actor
    method is absorbed by the handle's re-resolve retry — the caller never
    sees it."""
    from tests.test_runtime import Counter

    h = runtime.create_actor(Counter, (5,), name="connloss-victim")
    assert h.call("get") == 5
    faults.clear()
    try:
        rule = faults.inject("rpc.call", "connloss", match="incr", times=1)
        assert h.call("incr", 2) == 7  # transparent retry
        assert rule.fires == 1
    finally:
        faults.clear()
