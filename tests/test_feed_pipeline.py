"""The async double-buffered device feed (DevicePrefetcher) and its
per-phase instrumentation (ISSUE 1 tentpole).

Contract pinned here: the device-side prefetch stage only moves host
staging + ``device_put`` OFF the consumer's critical path — it must never
reorder, drop, or alter a batch (``prefetch_to_device=2`` bit-identical to
``=0`` through both estimators), it must propagate producer errors and shut
its threads down on early exit, and the ``decode/stage/h2d`` timers it
feeds must surface in the estimators' epoch reports (the measured split
VERDICT r5 Weak #2 asked for)."""

import time

import numpy as np
import pandas as pd
import pytest

from raydp_tpu.data.feed import DevicePrefetcher


# --------------------------------------------------------------- unit level
def test_device_prefetcher_order_and_values():
    items = list(range(57))
    out = list(DevicePrefetcher(iter(items), fn=lambda x: x * 2, depth=2))
    assert out == [x * 2 for x in items]


def test_device_prefetcher_propagates_producer_error():
    def gen():
        yield 1
        raise RuntimeError("decode failed")

    it = iter(DevicePrefetcher(gen(), depth=2))
    assert next(it) == 1
    with pytest.raises(RuntimeError, match="decode failed"):
        next(it)


def test_device_prefetcher_early_exit_stops_producer():
    """Abandoning the consumer mid-stream must stop the background thread
    (an estimator error must not leak one producer thread per epoch)."""
    produced = []

    def gen():
        for i in range(10_000):
            produced.append(i)
            yield i

    stage = DevicePrefetcher(gen(), depth=2)
    it = iter(stage)
    assert next(it) == 0
    it.close()
    stage._thread.join(timeout=5.0)
    assert not stage._thread.is_alive()
    n = len(produced)
    time.sleep(0.2)
    assert len(produced) == n  # nothing produced after close


def test_device_prefetcher_backpressure_bounds_readahead():
    """The bounded queue is the backpressure: the producer can be at most
    depth (queued) + 1 (in flight) + 1 (consumed) items ahead."""
    pulled = []

    def gen():
        for i in range(100):
            pulled.append(i)
            yield i

    stage = DevicePrefetcher(gen(), depth=2)
    it = iter(stage)
    assert next(it) == 0
    time.sleep(0.3)  # let the producer run as far ahead as it can
    assert len(pulled) <= 5
    assert list(it) == list(range(1, 100))  # drains cleanly afterwards


# ---------------------------------------------------------- estimator level
def _linear_df(session, n=1344):
    rng = np.random.RandomState(0)
    x = rng.random_sample((n, 2))
    y = x @ np.array([2.0, -3.0]) + 1.0
    return session.createDataFrame(
        pd.DataFrame({"x1": x[:, 0], "x2": x[:, 1], "y": y}),
        num_partitions=4)


@pytest.mark.slow
@pytest.mark.parametrize("chain", [1, 4])
def test_flax_prefetch_to_device_parity(session, monkeypatch, chain):
    """prefetch_to_device=2 must be BIT-IDENTICAL to =0 (same seed, same
    shuffle): the async stage only overlaps placement with compute — and it
    must compose with steps_per_dispatch chaining (the stacked path runs
    through the same prefetcher)."""
    import optax

    from raydp_tpu.data import from_frame
    from raydp_tpu.models import MLP
    from raydp_tpu.train import FlaxEstimator

    monkeypatch.setenv("RDT_DEVICE_CACHE", "0")  # pin the streaming feed
    ds = from_frame(_linear_df(session))

    def run(p2d):
        est = FlaxEstimator(
            model=MLP(features=(8,), use_batch_norm=False),
            optimizer=optax.adam(1e-2),
            loss="mse",
            feature_columns=["x1", "x2"],
            label_column="y",
            batch_size=64,
            num_epochs=2,
            shuffle=True,
            seed=0,
            steps_per_dispatch=chain,
            prefetch_to_device=p2d,
        )
        return est.fit(ds)

    sync = run(0)
    pipelined = run(2)
    assert [r["steps"] for r in sync.history] == \
        [r["steps"] for r in pipelined.history]
    for a, b in zip(sync.history, pipelined.history):
        assert a["train_loss"] == b["train_loss"]  # bit-identical


@pytest.mark.slow
def test_keras_prefetch_to_device_parity(session, monkeypatch):
    """The keras twin of the parity contract, over the jitted stateless
    loop."""
    import os

    os.environ.setdefault("KERAS_BACKEND", "jax")
    import keras

    from raydp_tpu.data import from_frame
    from raydp_tpu.train import KerasEstimator

    monkeypatch.setenv("RDT_DEVICE_CACHE", "0")
    ds = from_frame(_linear_df(session, n=448))

    def run(p2d):
        model = keras.Sequential([
            keras.layers.Input(shape=(2,)),
            keras.layers.Dense(16, activation="relu"),
            keras.layers.Dense(1),
        ])
        est = KerasEstimator(model=model, optimizer="adam", loss="mse",
                             feature_columns=["x1", "x2"], label_column="y",
                             batch_size=64, num_epochs=2, shuffle=True,
                             seed=0, prefetch_to_device=p2d)
        return est.fit(ds)

    sync = run(0)
    pipelined = run(2)
    assert len(sync.history) == len(pipelined.history) == 2
    for a, b in zip(sync.history, pipelined.history):
        assert a["loss"] == b["loss"]  # bit-identical


@pytest.mark.slow
def test_timing_split_surfaced_in_reports(session, monkeypatch):
    """Streaming epochs report a positive decode/stage/h2d split; the
    device-resident path reports zeros (nothing streamed). These keys are
    what bench.py aggregates into the detail record's per-phase split."""
    import optax

    from raydp_tpu.data import from_frame
    from raydp_tpu.models import MLP
    from raydp_tpu.train import FlaxEstimator

    ds = from_frame(_linear_df(session))

    def run():
        est = FlaxEstimator(
            model=MLP(features=(8,), use_batch_norm=False),
            optimizer=optax.adam(1e-2), loss="mse",
            feature_columns=["x1", "x2"], label_column="y",
            batch_size=64, num_epochs=2, shuffle=False,
            steps_per_dispatch=4)
        return est.fit(ds)

    monkeypatch.setenv("RDT_DEVICE_CACHE", "0")
    streamed = run()
    for r in streamed.history:
        assert r["decode_time_s"] > 0.0
        assert r["stage_time_s"] > 0.0  # the chained np.stack assembly
        assert r["h2d_time_s"] > 0.0

    monkeypatch.setenv("RDT_DEVICE_CACHE", "1")
    resident = run()
    for r in resident.history:
        assert r["decode_time_s"] == 0.0
        assert r["stage_time_s"] == 0.0
        assert r["h2d_time_s"] == 0.0


def test_device_feed_prefetch_knob_env_default(session, monkeypatch):
    """prefetch_to_device falls back to RDT_PREFETCH_TO_DEVICE (default 2);
    an explicit argument wins."""
    from raydp_tpu.data import from_frame
    from raydp_tpu.data.feed import DeviceFeed

    ds = from_frame(_linear_df(session, n=256))
    cols = {"features": (["x1", "x2"], np.float32),
            "label": ("y", np.float32)}
    assert DeviceFeed(ds, 64, cols).prefetch_to_device == 2
    monkeypatch.setenv("RDT_PREFETCH_TO_DEVICE", "5")
    assert DeviceFeed(ds, 64, cols).prefetch_to_device == 5
    assert DeviceFeed(ds, 64, cols,
                      prefetch_to_device=0).prefetch_to_device == 0
