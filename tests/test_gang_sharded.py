"""Gang training with parameters sharded ACROSS processes.

The reference's Ray Train path only replicates (DDP, torch/estimator.py:243);
sharding model state over the gang (fsdp/expert axes spanning hosts) is the
TPU-native capability that makes pod-scale DLRM embeddings possible
(SURVEY.md §7 step 5 / BASELINE.json "Criteo DLRM pod-scale" config). These
tests run a real 2-process ``jax.distributed`` gang where no single process
ever holds the full state on device, exercising:

- the sharded multi-writer checkpoint format (train/checkpoint.py),
- batch-row derivation from the actual batch sharding
  (``process_local_batch_rows``): proper slices under a >1 data axis,
  full-batch replication under a size-1 data axis (pure fsdp/expert),
- ``process_allgather`` assembly of the trained model.
"""

import numpy as np
import pandas as pd

from raydp_tpu.models import MLP
from raydp_tpu.parallel import MeshSpec
from raydp_tpu.train import FlaxEstimator

NUM_DENSE = 4
CAT_SIZES = [32, 16, 48, 64]


def _linear_df(session, n=1536, parts=4):
    rng = np.random.RandomState(0)
    x = rng.random_sample((n, 2))
    y = x @ np.array([2.0, -3.0]) + 1.0 + rng.normal(0, 0.01, n)
    pdf = pd.DataFrame({"x1": x[:, 0], "x2": x[:, 1], "y": y})
    return session.createDataFrame(pdf, num_partitions=parts)


def _mlp_estimator(mesh_spec=None, num_epochs=3, ckpt_dir=None):
    import optax

    return FlaxEstimator(
        model=MLP(features=(32, 16), use_batch_norm=False),
        optimizer=optax.sgd(5e-2),
        loss="mse",
        feature_columns=["x1", "x2"],
        label_column="y",
        batch_size=64,
        num_epochs=num_epochs,
        mesh_spec=mesh_spec,
        shuffle=False,
        checkpoint_dir=ckpt_dir,
    )


def test_process_local_batch_rows_single_process():
    from raydp_tpu.data.feed import process_local_batch_rows
    from raydp_tpu.parallel import batch_sharding, make_mesh

    # every device is local → the full range, whatever the mesh shape
    for spec in (MeshSpec(), MeshSpec(fsdp=8), MeshSpec(expert=8),
                 MeshSpec(data=2, fsdp=4)):
        mesh = make_mesh(spec)
        assert process_local_batch_rows(batch_sharding(mesh), 64) == (0, 64)


def test_gang_iterator_explicit_row_range():
    """row_range=(0, B) on every rank = full-batch replication semantics."""
    import pyarrow as pa

    from raydp_tpu.data.feed import GangShardIterator

    rows = np.arange(32, dtype=np.float64)

    class _Ds:
        def block_sizes(self):
            return [32]

        def get_block(self, i, zero_copy=False):
            return pa.table({"x": rows})

    for rank in (0, 1):
        it = GangShardIterator(_Ds(), global_batch=16, world_size=2, rank=rank,
                               columns={"x": ("x", np.float64)},
                               row_range=(0, 16))
        batches = list(it)
        assert [b["x"].shape for b in batches] == [(16,), (16,)]
        np.testing.assert_array_equal(batches[0]["x"], rows[:16])


def test_gang_fsdp_params_sharded_across_processes(session, tmp_path):
    """fsdp=16 over 2 processes × 8 devices: every weight matrix is sharded
    across the process boundary; losses must still match the single-process
    run (SPMD sharding changes nothing about the math)."""
    from raydp_tpu.data.dataset import from_frame

    df = _linear_df(session)
    ds = from_frame(df)

    single = _mlp_estimator(ckpt_dir=str(tmp_path / "single"))
    r1 = single.fit(ds)

    gang = _mlp_estimator(mesh_spec=MeshSpec(fsdp=16),
                          ckpt_dir=str(tmp_path / "gang"))
    r2 = gang.fit_gang(ds, num_workers=2, run_timeout=900.0)

    np.testing.assert_allclose(
        [h["train_loss"] for h in r2.history],
        [h["train_loss"] for h in r1.history], rtol=2e-4)
    # the allgathered model matches the single-process weights
    k1 = np.asarray(single.get_model()["params"]["Dense_0"]["kernel"])
    k2 = np.asarray(gang.get_model()["params"]["Dense_0"]["kernel"])
    assert k2.shape == k1.shape  # full (unsharded) host copy came back
    np.testing.assert_allclose(k2, k1, rtol=1e-3, atol=1e-4)


def test_gang_sharded_checkpoint_resume(session, tmp_path):
    """A second gang over the same checkpoint dir resumes from the sharded
    multi-writer checkpoint instead of retraining."""
    from raydp_tpu.data.dataset import from_frame
    import raydp_tpu.train.checkpoint as ckpt

    df = _linear_df(session, n=1024)
    ds = from_frame(df)
    ckpt_dir = str(tmp_path / "ck")

    first = _mlp_estimator(mesh_spec=MeshSpec(fsdp=16), num_epochs=2,
                           ckpt_dir=ckpt_dir)
    r1 = first.fit_gang(ds, num_workers=2, run_timeout=900.0)
    assert [h["epoch"] for h in r1.history] == [0, 1]
    # the sharded format is on disk: per-process manifests + COMPLETE marker
    import glob as _glob
    import os
    steps = [p for p in _glob.glob(os.path.join(ckpt_dir, "step_*"))]
    assert steps
    latest = sorted(steps, key=lambda p: int(p.rsplit("_", 1)[1]))[-1]
    assert len(_glob.glob(os.path.join(latest, "manifest_*.json"))) == 2
    assert os.path.exists(os.path.join(latest, "COMPLETE"))

    second = _mlp_estimator(mesh_spec=MeshSpec(fsdp=16), num_epochs=4,
                            ckpt_dir=ckpt_dir)
    r2 = second.fit_gang(ds, num_workers=2, run_timeout=900.0)
    # epochs 0-1 came from the restored sidecar; 2-3 were trained
    assert [h["epoch"] for h in r2.history] == [0, 1, 2, 3]
    assert r2.history[-1]["train_loss"] < r1.history[-1]["train_loss"]
    assert ckpt.restore_extra(ckpt_dir)["history"]


def test_gang_expert_sharded_dlrm(session, tmp_path):
    """expert=16 (data axis size 1) over 2 processes: embedding tables sharded
    across the process boundary, batch REPLICATED on every process — the
    row-range derivation must feed the full global batch from each rank."""
    import optax

    from raydp_tpu.data.dataset import from_frame
    from raydp_tpu.models import DLRM, criteo_batch_preprocessor, \
        dlrm_param_rules

    rng = np.random.RandomState(0)
    n = 1024
    data = {"label": rng.randint(0, 2, n).astype(np.float64)}
    for i in range(NUM_DENSE):
        data[f"d{i}"] = rng.random_sample(n)
    for j, vocab in enumerate(CAT_SIZES):
        data[f"c{j}"] = rng.randint(0, vocab, n)
    df = session.createDataFrame(pd.DataFrame(data), num_partitions=4)
    ds = from_frame(df)
    features = [f"d{i}" for i in range(NUM_DENSE)] + \
        [f"c{j}" for j in range(len(CAT_SIZES))]

    def make_est(mesh_spec, ckpt_dir):
        return FlaxEstimator(
            model=DLRM(categorical_sizes=CAT_SIZES, num_dense=NUM_DENSE,
                       embedding_dim=8, bottom_mlp=(16, 8),
                       top_mlp=(32, 16, 1)),
            optimizer=optax.sgd(0.05),
            loss="bce_with_logits",
            feature_columns=features,
            label_column="label",
            feature_dtype=np.float64,
            batch_size=128,
            num_epochs=2,
            mesh_spec=mesh_spec,
            shuffle=False,
            param_rules=dlrm_param_rules("expert"),
            batch_preprocessor=criteo_batch_preprocessor(NUM_DENSE),
            checkpoint_dir=ckpt_dir,
        )

    single = make_est(MeshSpec(expert=8), str(tmp_path / "single"))
    r1 = single.fit(ds)

    gang = make_est(MeshSpec(expert=16), str(tmp_path / "gang"))
    r2 = gang.fit_gang(ds, num_workers=2, run_timeout=900.0)

    np.testing.assert_allclose(
        [h["train_loss"] for h in r2.history],
        [h["train_loss"] for h in r1.history], rtol=5e-4)
    emb1 = np.asarray(single.get_model()["params"]["embedding_0"]["embedding"])
    emb2 = np.asarray(gang.get_model()["params"]["embedding_0"]["embedding"])
    assert emb2.shape == emb1.shape
    np.testing.assert_allclose(emb2, emb1, rtol=1e-3, atol=1e-4)
