"""Gang training with parameters sharded ACROSS processes.

The reference's Ray Train path only replicates (DDP, torch/estimator.py:243);
sharding model state over the gang (fsdp/expert axes spanning hosts) is the
TPU-native capability that makes pod-scale DLRM embeddings possible
(SURVEY.md §7 step 5 / BASELINE.json "Criteo DLRM pod-scale" config). These
tests run a real 2-process ``jax.distributed`` gang where no single process
ever holds the full state on device, exercising:

- the sharded multi-writer checkpoint format (train/checkpoint.py),
- batch-row derivation from the actual batch sharding
  (``process_local_batch_rows``): proper slices under a >1 data axis,
  full-batch replication under a size-1 data axis (pure fsdp/expert),
- ``process_allgather`` assembly of the trained model.
"""

import numpy as np
import pandas as pd

from raydp_tpu.models import MLP
from raydp_tpu.parallel import MeshSpec
from raydp_tpu.train import FlaxEstimator

NUM_DENSE = 4
CAT_SIZES = [32, 16, 48, 64]


def _linear_df(session, n=1536, parts=4):
    rng = np.random.RandomState(0)
    x = rng.random_sample((n, 2))
    y = x @ np.array([2.0, -3.0]) + 1.0 + rng.normal(0, 0.01, n)
    pdf = pd.DataFrame({"x1": x[:, 0], "x2": x[:, 1], "y": y})
    return session.createDataFrame(pdf, num_partitions=parts)


def _mlp_estimator(mesh_spec=None, num_epochs=3, ckpt_dir=None, **kw):
    import optax

    return FlaxEstimator(
        model=MLP(features=(32, 16), use_batch_norm=False),
        optimizer=optax.sgd(5e-2),
        loss="mse",
        feature_columns=["x1", "x2"],
        label_column="y",
        batch_size=64,
        num_epochs=num_epochs,
        mesh_spec=mesh_spec,
        shuffle=False,
        checkpoint_dir=ckpt_dir,
        **kw,
    )


def _single_device_mesh():
    """A 1-device mesh: the unsharded ground truth every mesh shape must
    reproduce (SPMD sharding is a layout, not a math change)."""
    import jax

    from raydp_tpu.parallel import make_mesh

    return make_mesh(MeshSpec(), devices=jax.devices()[:1])


def test_process_local_batch_rows_single_process():
    from raydp_tpu.data.feed import process_local_batch_rows
    from raydp_tpu.parallel import batch_sharding, make_mesh

    # every device is local → the full range, whatever the mesh shape
    for spec in (MeshSpec(), MeshSpec(fsdp=8), MeshSpec(expert=8),
                 MeshSpec(data=2, fsdp=4)):
        mesh = make_mesh(spec)
        assert process_local_batch_rows(batch_sharding(mesh), 64) == (0, 64)


def test_gang_iterator_explicit_row_range():
    """row_range=(0, B) on every rank = full-batch replication semantics."""
    import pyarrow as pa

    from raydp_tpu.data.feed import GangShardIterator

    rows = np.arange(32, dtype=np.float64)

    class _Ds:
        def block_sizes(self):
            return [32]

        def get_block(self, i, zero_copy=False):
            return pa.table({"x": rows})

    for rank in (0, 1):
        it = GangShardIterator(_Ds(), global_batch=16, world_size=2, rank=rank,
                               columns={"x": ("x", np.float64)},
                               row_range=(0, 16))
        batches = list(it)
        assert [b["x"].shape for b in batches] == [(16,), (16,)]
        np.testing.assert_array_equal(batches[0]["x"], rows[:16])


def test_gang_fsdp_params_sharded_across_processes(session, tmp_path):
    """fsdp=16 over 2 processes × 8 devices: every weight matrix is sharded
    across the process boundary; losses must still match the single-process
    run (SPMD sharding changes nothing about the math)."""
    from raydp_tpu.data.dataset import from_frame

    df = _linear_df(session)
    ds = from_frame(df)

    single = _mlp_estimator(ckpt_dir=str(tmp_path / "single"))
    r1 = single.fit(ds)

    gang = _mlp_estimator(mesh_spec=MeshSpec(fsdp=16),
                          ckpt_dir=str(tmp_path / "gang"))
    r2 = gang.fit_gang(ds, num_workers=2, run_timeout=900.0)

    np.testing.assert_allclose(
        [h["train_loss"] for h in r2.history],
        [h["train_loss"] for h in r1.history], rtol=2e-4)
    # the allgathered model matches the single-process weights
    k1 = np.asarray(single.get_model()["params"]["Dense_0"]["kernel"])
    k2 = np.asarray(gang.get_model()["params"]["Dense_0"]["kernel"])
    assert k2.shape == k1.shape  # full (unsharded) host copy came back
    np.testing.assert_allclose(k2, k1, rtol=1e-3, atol=1e-4)


def test_gang_sharded_checkpoint_resume(session, tmp_path):
    """A second gang over the same checkpoint dir resumes from the sharded
    multi-writer checkpoint instead of retraining."""
    from raydp_tpu.data.dataset import from_frame
    import raydp_tpu.train.checkpoint as ckpt

    df = _linear_df(session, n=1024)
    ds = from_frame(df)
    ckpt_dir = str(tmp_path / "ck")

    first = _mlp_estimator(mesh_spec=MeshSpec(fsdp=16), num_epochs=2,
                           ckpt_dir=ckpt_dir)
    r1 = first.fit_gang(ds, num_workers=2, run_timeout=900.0)
    assert [h["epoch"] for h in r1.history] == [0, 1]
    # the sharded format is on disk: per-process manifests + COMPLETE marker
    import glob as _glob
    import os
    steps = [p for p in _glob.glob(os.path.join(ckpt_dir, "step_*"))]
    assert steps
    latest = sorted(steps, key=lambda p: int(p.rsplit("_", 1)[1]))[-1]
    assert len(_glob.glob(os.path.join(latest, "manifest_*.json"))) == 2
    assert os.path.exists(os.path.join(latest, "COMPLETE"))

    second = _mlp_estimator(mesh_spec=MeshSpec(fsdp=16), num_epochs=4,
                            ckpt_dir=ckpt_dir)
    r2 = second.fit_gang(ds, num_workers=2, run_timeout=900.0)
    # epochs 0-1 came from the restored sidecar; 2-3 were trained
    assert [h["epoch"] for h in r2.history] == [0, 1, 2, 3]
    assert r2.history[-1]["train_loss"] < r1.history[-1]["train_loss"]
    assert ckpt.restore_extra(ckpt_dir)["history"]


def test_gang_expert_sharded_dlrm(session, tmp_path):
    """expert=16 (data axis size 1) over 2 processes: embedding tables sharded
    across the process boundary, batch REPLICATED on every process — the
    row-range derivation must feed the full global batch from each rank."""
    import optax

    from raydp_tpu.data.dataset import from_frame
    from raydp_tpu.models import DLRM, criteo_batch_preprocessor, \
        dlrm_param_rules

    rng = np.random.RandomState(0)
    n = 1024
    data = {"label": rng.randint(0, 2, n).astype(np.float64)}
    for i in range(NUM_DENSE):
        data[f"d{i}"] = rng.random_sample(n)
    for j, vocab in enumerate(CAT_SIZES):
        data[f"c{j}"] = rng.randint(0, vocab, n)
    df = session.createDataFrame(pd.DataFrame(data), num_partitions=4)
    ds = from_frame(df)
    features = [f"d{i}" for i in range(NUM_DENSE)] + \
        [f"c{j}" for j in range(len(CAT_SIZES))]

    def make_est(mesh_spec, ckpt_dir):
        return FlaxEstimator(
            model=DLRM(categorical_sizes=CAT_SIZES, num_dense=NUM_DENSE,
                       embedding_dim=8, bottom_mlp=(16, 8),
                       top_mlp=(32, 16, 1)),
            optimizer=optax.sgd(0.05),
            loss="bce_with_logits",
            feature_columns=features,
            label_column="label",
            feature_dtype=np.float64,
            batch_size=128,
            num_epochs=2,
            mesh_spec=mesh_spec,
            shuffle=False,
            param_rules=dlrm_param_rules("expert"),
            batch_preprocessor=criteo_batch_preprocessor(NUM_DENSE),
            checkpoint_dir=ckpt_dir,
        )

    single = make_est(MeshSpec(expert=8), str(tmp_path / "single"))
    r1 = single.fit(ds)

    gang = make_est(MeshSpec(expert=16), str(tmp_path / "gang"))
    r2 = gang.fit_gang(ds, num_workers=2, run_timeout=900.0)

    np.testing.assert_allclose(
        [h["train_loss"] for h in r2.history],
        [h["train_loss"] for h in r1.history], rtol=5e-4)
    emb1 = np.asarray(single.get_model()["params"]["embedding_0"]["embedding"])
    emb2 = np.asarray(gang.get_model()["params"]["embedding_0"]["embedding"])
    assert emb2.shape == emb1.shape
    np.testing.assert_allclose(emb2, emb1, rtol=1e-3, atol=1e-4)


# ---- single-process mesh matrix (8 virtual devices, PR 16) ------------------
# The role policy + pad-and-mask feed path, exercised where the container
# can run them: one process, 8 virtual CPU devices. The 2-process tests
# above cover the cross-process variants of the same machinery.


def test_role_policy_classify_and_specs():
    """The SpecLayout-style role classifier: path+shape → role → spec."""
    from jax.sharding import PartitionSpec as P

    from raydp_tpu.parallel import make_mesh
    from raydp_tpu.parallel.roles import classify_param, role_partition_spec

    assert classify_param("params/embedding_0/embedding", (32, 8)) \
        == "embedding"
    assert classify_param("params/Dense_0/kernel", (16, 8)) == "kernel"
    assert classify_param("params/Dense_0/bias", (8,)) == "replicated"
    # optimizer-state mirrors classify like the parameter itself
    assert classify_param("opt_state/0/mu/Dense_0/kernel", (16, 8)) \
        == "kernel"

    mesh = make_mesh(dict(fsdp=4, tensor=2))
    # embedding rows span fsdp×tensor when the product divides the vocab
    assert role_partition_spec(mesh, "params/embed/embedding", (32, 8)) \
        == P(("fsdp", "tensor"), None)
    # kernels: tensor on the output dim, fsdp on the largest remaining
    assert role_partition_spec(mesh, "params/Dense_0/kernel", (16, 8)) \
        == P("fsdp", "tensor")
    # ≤1-D replicates; indivisible dims degrade axis by axis, never raise
    assert role_partition_spec(mesh, "params/Dense_0/bias", (8,)) == P()
    assert role_partition_spec(mesh, "params/Dense_0/kernel", (3, 5)) \
        == P(None, None)
    # tensor-only fit on the vocab when fsdp does not divide
    mesh2 = make_mesh(dict(fsdp=4, tensor=2))
    assert role_partition_spec(mesh2, "params/embed/embedding", (6, 4)) \
        == P("tensor", None)


def test_optimizer_state_inherits_param_specs():
    """Adam moments mirror the parameter paths/shapes, so the role policy
    shards them identically — the FSDP memory win covers the optimizer."""
    import jax
    import jax.numpy as jnp
    import optax
    from flax.training import train_state

    from raydp_tpu.parallel import make_mesh, param_sharding_rules

    mesh = make_mesh(dict(fsdp=4, tensor=2))
    model = MLP(features=(32, 16), use_batch_norm=False)
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 2)))
    state = train_state.TrainState.create(
        apply_fn=model.apply, params=variables["params"],
        tx=optax.adam(1e-3))
    sh = param_sharding_rules(mesh, None)(state)
    mu = sh.opt_state[0].mu
    p_leaves = jax.tree.leaves(sh.params)
    m_leaves = jax.tree.leaves(mu)
    assert len(p_leaves) == len(m_leaves)
    for p_s, m_s in zip(p_leaves, m_leaves):
        assert p_s.spec == m_s.spec
    # at least one kernel actually sharded (the policy is not a no-op here)
    assert any(tuple(s.spec) for s in p_leaves)


def test_mesh_equivalence_matrix(session):
    """dp / fsdp / fsdp×tp from mesh_spec alone (no param_rules): per-epoch
    losses match the single-device run — sharding changes the layout, not
    the math. Also the dict-valued mesh_spec path."""
    from raydp_tpu.data.dataset import from_frame

    ds = from_frame(_linear_df(session))
    base = _mlp_estimator(mesh=_single_device_mesh())
    losses0 = [h["train_loss"] for h in base.fit(ds).history]

    for spec in (MeshSpec(), MeshSpec(fsdp=8), dict(fsdp=4, tensor=2)):
        est = _mlp_estimator(mesh_spec=spec)
        r = est.fit(ds)
        np.testing.assert_allclose(
            [h["train_loss"] for h in r.history], losses0, rtol=5e-4,
            err_msg=f"mesh_spec={spec}")

    # the last (fsdp=4 × tensor=2) state is really sharded by role:
    # Dense_1 kernel (32, 16) → fsdp on the input dim, tensor on the output
    from jax.sharding import PartitionSpec as P

    k = est.get_state().params["Dense_1"]["kernel"]
    assert k.sharding.spec == P("fsdp", "tensor")


def test_train_ragged_tail_pad_parity(session):
    """drop_last=False with a 28-row tail (1500 = 23×64 + 28): under an
    8-way data extent the tail pads-and-masks to a full batch — same step
    count and same per-epoch losses as the single-device run that consumes
    the ragged batch natively. Before PR 16 this config could not even
    place the tail (28 rows do not divide over 8 devices)."""
    from raydp_tpu.data.dataset import from_frame

    ds = from_frame(_linear_df(session, n=1500))

    base = _mlp_estimator(mesh=_single_device_mesh(), drop_last=False)
    r0 = base.fit(ds)
    assert [h["steps"] for h in r0.history] == [24, 24, 24]

    sharded = _mlp_estimator(mesh_spec=MeshSpec(fsdp=8), drop_last=False)
    r1 = sharded.fit(ds)
    assert [h["steps"] for h in r1.history] == [24, 24, 24]
    np.testing.assert_allclose(
        [h["train_loss"] for h in r1.history],
        [h["train_loss"] for h in r0.history], rtol=5e-4)


def test_eval_ragged_tail_pad_parity(session, monkeypatch):
    """The eval tail (300 = 4×64 + 44) is padded-and-masked instead of
    dropped under a >1 data extent, on BOTH eval paths: the device-resident
    scan (tail padded in-jit) and the streaming feed (tail padded on the
    host). eval_loss must match the single-device run exactly because the
    mask keeps padded rows out of the loss AND the row count."""
    from raydp_tpu.data.dataset import from_frame

    train = from_frame(_linear_df(session, n=1024))
    ev = from_frame(_linear_df(session, n=300, parts=2))

    base = _mlp_estimator(mesh=_single_device_mesh(), metrics=["mae"])
    e0 = base.fit(train, ev).history[-1]

    # device-resident eval cache: the ragged tail pads inside the jit
    cached = _mlp_estimator(mesh_spec=MeshSpec(fsdp=8), metrics=["mae"])
    e1 = cached.fit(train, ev).history[-1]
    np.testing.assert_allclose(e1["eval_loss"], e0["eval_loss"], rtol=5e-4)
    np.testing.assert_allclose(e1["eval_mae"], e0["eval_mae"], rtol=5e-4)

    # streaming eval feed: pad_batch on the host side of the prefetcher
    monkeypatch.setenv("RDT_DEVICE_CACHE", "0")
    streamed = _mlp_estimator(mesh_spec=MeshSpec(fsdp=8), metrics=["mae"])
    e2 = streamed.fit(train, ev).history[-1]
    np.testing.assert_allclose(e2["eval_loss"], e0["eval_loss"], rtol=5e-4)
    np.testing.assert_allclose(e2["eval_mae"], e0["eval_mae"], rtol=5e-4)


def test_pad_tail_knob_restores_drop(session, monkeypatch):
    """RDT_TRAIN_PAD_TAIL=0 is the escape hatch back to the pre-PR-16 drop:
    a 40-row online epoch under fsdp=8 (batch 64) then yields no step at
    all, where padding turns it into one masked step."""
    from raydp_tpu.data.dataset import from_frame

    ds = from_frame(_linear_df(session, n=40, parts=2))

    est = _mlp_estimator(mesh_spec=MeshSpec(fsdp=8))
    r1 = est._partial_fit_epoch(ds, 0)
    assert r1["steps"] == 1
    assert np.isfinite(r1["train_loss"])

    monkeypatch.setenv("RDT_TRAIN_PAD_TAIL", "0")
    est2 = _mlp_estimator(mesh_spec=MeshSpec(fsdp=8))
    r2 = est2._partial_fit_epoch(ds, 0)
    assert r2["steps"] == 0


def test_checkpoint_roundtrip_across_mesh_shapes(session, tmp_path):
    """Train under fsdp=2, restore the checkpoint into a dp-only mesh:
    restore_placed reassembles full values under the NEW shardings — a
    topology change between save and restore is routine (autoscale)."""
    import jax

    from raydp_tpu.data.dataset import from_frame
    from raydp_tpu.parallel import make_mesh, param_sharding_rules
    from raydp_tpu.train import checkpoint as ckpt

    ds = from_frame(_linear_df(session, n=1024))
    ckpt_dir = str(tmp_path / "ck")
    est = _mlp_estimator(mesh_spec=dict(fsdp=2), num_epochs=2,
                         ckpt_dir=ckpt_dir)
    est.fit(ds)
    trained = est.get_state()

    dp_mesh = make_mesh(MeshSpec())  # data=8: every param replicated
    shardings = param_sharding_rules(dp_mesh, None)(trained)
    restored, step = ckpt.restore_placed(ckpt_dir, trained, shardings)
    assert step == 1
    for a, b in zip(jax.tree.leaves(trained), jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # and the restored tree really lives under the dp mesh's shardings
    from jax.sharding import PartitionSpec as P

    k = restored.params["Dense_1"]["kernel"]
    assert k.sharding.mesh.shape["fsdp"] == 1
    assert k.sharding.spec == P()


def test_sharded_export_serve_bitwise_matches_predict(session, tmp_path):
    """export_serving off an fsdp×tp-trained state → load_servable →
    predict_table is bit-identical to the estimator's own predict: the
    export gathered exactly the trained weights."""
    import pyarrow as pa

    from raydp_tpu.data.dataset import from_frame
    from raydp_tpu.serve.servable import load_servable

    rng = np.random.RandomState(0)
    x = rng.random_sample((512, 2))
    y = x @ np.array([2.0, -3.0]) + 1.0
    pdf = pd.DataFrame({"x1": x[:, 0], "x2": x[:, 1], "y": y})
    df = session.createDataFrame(pdf, num_partitions=2)
    ds = from_frame(df)

    est = _mlp_estimator(mesh_spec=dict(fsdp=4, tensor=2), num_epochs=2)
    est.fit(ds)
    ref = est.predict(from_frame(df.select("x1", "x2")))

    sv = load_servable(est.export_serving(str(tmp_path / "bundle")))
    got = sv.predict_table(pa.table({"x1": pdf["x1"].values,
                                     "x2": pdf["x2"].values}))
    assert np.array_equal(got, ref)


# ---- activation-side parallelism (PR 17): accum × remat × seq ---------------
# Gradient accumulation, role-driven rematerialization and seq-axis
# activation sharding are residency/layout levers — every test here is a
# parity contract against the unaccumulated / unsharded run.


def test_accum_parity_across_meshes(session):
    """accum=4 reproduces the accum=1 per-epoch loss trajectory on dp,
    fsdp and fsdp×tp meshes: row-weighted microbatch accumulation is the
    same math as the full-batch step, whatever the param layout."""
    from raydp_tpu.data.dataset import from_frame

    ds = from_frame(_linear_df(session))
    losses0 = [h["train_loss"]
               for h in _mlp_estimator(mesh_spec=MeshSpec()).fit(ds).history]

    for spec in (MeshSpec(), MeshSpec(fsdp=8), dict(fsdp=4, tensor=2)):
        r = _mlp_estimator(mesh_spec=spec, accum_steps=4).fit(ds)
        np.testing.assert_allclose(
            [h["train_loss"] for h in r.history], losses0, rtol=5e-4,
            err_msg=f"accum=4 diverged on mesh_spec={spec}")

    # the engaged plane publishes its telemetry: the accumulation factor
    # and the compiled step's peak temp bytes (XLA memory_analysis)
    from raydp_tpu import metrics

    snap = metrics.snapshot()["gauges"]
    assert snap["train_accum_steps"][""] == 4
    assert snap["train_activation_bytes_per_process"][""] > 0


def test_accum_knob_matches_constructor(session, monkeypatch):
    """RDT_TRAIN_ACCUM_STEPS=4 builds the identical step program as
    accum_steps=4 — same losses bitwise — and an accum that does not
    divide the batch fails loudly, not by silently truncating rows."""
    import pytest

    from raydp_tpu.data.dataset import from_frame

    ds = from_frame(_linear_df(session, n=1024))
    r1 = _mlp_estimator(mesh_spec=MeshSpec(), accum_steps=4).fit(ds)
    monkeypatch.setenv("RDT_TRAIN_ACCUM_STEPS", "4")
    r2 = _mlp_estimator(mesh_spec=MeshSpec()).fit(ds)
    monkeypatch.delenv("RDT_TRAIN_ACCUM_STEPS")
    np.testing.assert_array_equal(
        [h["train_loss"] for h in r2.history],
        [h["train_loss"] for h in r1.history])

    with pytest.raises(ValueError, match="divide"):
        _mlp_estimator(mesh_spec=MeshSpec(), accum_steps=5).fit(ds)


def test_remat_modes_identical_losses(session):
    """jax.checkpoint placement (none/dots/full) recomputes, never
    approximates: loss trajectories agree to float-summation noise (the
    recompute can re-associate reductions, nothing more) across remat
    modes, with accumulation and an fsdp mesh engaged."""
    from raydp_tpu.data.dataset import from_frame

    ds = from_frame(_linear_df(session, n=1024))
    ref = _mlp_estimator(mesh_spec=MeshSpec(fsdp=8), accum_steps=4,
                         remat="none").fit(ds)
    for mode in ("dots", "full"):
        r = _mlp_estimator(mesh_spec=MeshSpec(fsdp=8), accum_steps=4,
                           remat=mode).fit(ds)
        np.testing.assert_allclose(
            [h["train_loss"] for h in r.history],
            [h["train_loss"] for h in ref.history], rtol=1e-6,
            err_msg=f"remat={mode} changed the math")


def test_seq_sharded_parity(session):
    """data=4 × seq=2: feature dims shard over the seq axis on top of the
    batch dim — a pure layout change, so per-epoch losses match the
    seq-less dp run and per-row predictions agree tightly."""
    from raydp_tpu.data.dataset import from_frame

    df = _linear_df(session)
    ds = from_frame(df)
    base = _mlp_estimator(mesh_spec=MeshSpec())
    r0 = base.fit(ds)

    seq = _mlp_estimator(mesh_spec=dict(data=4, seq=2))
    r1 = seq.fit(ds)
    np.testing.assert_allclose(
        [h["train_loss"] for h in r1.history],
        [h["train_loss"] for h in r0.history], rtol=5e-4)

    feats = from_frame(df.select("x1", "x2"))
    np.testing.assert_allclose(seq.predict(feats), base.predict(feats),
                               rtol=1e-4, atol=1e-6)


def test_seq_sharded_with_accum_and_remat(session):
    """The full activation plane at once — accum=4 × remat=full ×
    data=4/seq=2 — still lands the plain single-mesh trajectory."""
    from raydp_tpu.data.dataset import from_frame

    ds = from_frame(_linear_df(session))
    losses0 = [h["train_loss"]
               for h in _mlp_estimator(mesh_spec=MeshSpec()).fit(ds).history]
    r = _mlp_estimator(mesh_spec=dict(data=4, seq=2), accum_steps=4,
                       remat="full").fit(ds)
    np.testing.assert_allclose(
        [h["train_loss"] for h in r.history], losses0, rtol=5e-4)


def test_accum_ragged_tail_partial_fit(session):
    """40 rows, batch 64, accum=4 under fsdp=8: the padded tail splits
    into microbatches where the LAST is all padding — its rows-weight is
    zero, so the masked online step still matches the unaccumulated one."""
    from raydp_tpu.data.dataset import from_frame

    ds = from_frame(_linear_df(session, n=40, parts=2))

    plain = _mlp_estimator(mesh_spec=MeshSpec(fsdp=8))._partial_fit_epoch(
        ds, 0)
    accum = _mlp_estimator(
        mesh_spec=MeshSpec(fsdp=8), accum_steps=4)._partial_fit_epoch(ds, 0)
    assert accum["steps"] == plain["steps"] == 1
    np.testing.assert_allclose(accum["train_loss"], plain["train_loss"],
                               rtol=5e-4)


def test_accum_checkpoint_roundtrip(session, tmp_path):
    """Accumulation holds no state across optimizer steps: a checkpoint
    written by an accum=4 fit restores bit-identically to the live state,
    and a longer accum=4 run resumes from it epoch-for-epoch."""
    import jax

    from raydp_tpu.data.dataset import from_frame
    from raydp_tpu.parallel import param_sharding_rules
    from raydp_tpu.train import checkpoint as ckpt

    ds = from_frame(_linear_df(session, n=1024))
    ckpt_dir = str(tmp_path / "ck")
    est = _mlp_estimator(mesh_spec=MeshSpec(fsdp=8), num_epochs=2,
                         ckpt_dir=ckpt_dir, accum_steps=4)
    r1 = est.fit(ds)
    trained = est.get_state()
    shardings = param_sharding_rules(trained.params["Dense_0"]["kernel"]
                                     .sharding.mesh, None)(trained)
    restored, step = ckpt.restore_placed(ckpt_dir, trained, shardings)
    assert step == 1
    for a, b in zip(jax.tree.leaves(trained), jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    resumed = _mlp_estimator(mesh_spec=MeshSpec(fsdp=8), num_epochs=4,
                             ckpt_dir=ckpt_dir, accum_steps=4)
    r2 = resumed.fit(ds)
    assert [h["epoch"] for h in r2.history] == [0, 1, 2, 3]
    np.testing.assert_allclose(
        [h["train_loss"] for h in r2.history[:2]],
        [h["train_loss"] for h in r1.history], rtol=1e-6)
    assert r2.history[-1]["train_loss"] < r1.history[-1]["train_loss"]
