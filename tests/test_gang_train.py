"""Gang-distributed FlaxEstimator training.

Parity: the reference trains in N Ray Train worker processes with
``FailureConfig`` (torch/estimator.py:312-356). Here ``fit_gang`` runs one
process per host under ``SPMDJob(jax_distributed=True)``: every rank feeds its
slice of each global batch through ``make_array_from_process_local_data``,
rank 0 writes orbax checkpoints, and a rank failure restarts the gang from the
last checkpoint. The core correctness claim — distributing changed nothing —
is asserted by matching per-epoch losses against the single-process run.
"""

import os

import numpy as np
import pandas as pd
import pytest

from raydp_tpu.models import MLP
from raydp_tpu.train import FlaxEstimator


def _linear_df(session, n=2048, parts=4):
    rng = np.random.RandomState(0)
    x = rng.random_sample((n, 2))
    y = x @ np.array([2.0, -3.0]) + 1.0 + rng.normal(0, 0.01, n)
    pdf = pd.DataFrame({"x1": x[:, 0], "x2": x[:, 1], "y": y})
    return session.createDataFrame(pdf, num_partitions=parts)


def _estimator(num_epochs=3, callbacks=None, ckpt_dir=None,
               steps_per_dispatch=1):
    import optax

    return FlaxEstimator(
        model=MLP(features=(16,), use_batch_norm=False),
        optimizer=optax.adam(1e-2),
        loss="mse",
        feature_columns=["x1", "x2"],
        label_column="y",
        batch_size=64,
        num_epochs=num_epochs,
        shuffle=False,
        checkpoint_dir=ckpt_dir,
        callbacks=callbacks,
        steps_per_dispatch=steps_per_dispatch,
    )


def test_gang_losses_match_single_process(session, tmp_path):
    from raydp_tpu.data.dataset import from_frame

    df = _linear_df(session)
    train_df, test_df = df.randomSplit([0.75, 0.25], seed=1)
    train_ds, test_ds = from_frame(train_df), from_frame(test_df)

    single = _estimator(ckpt_dir=str(tmp_path / "single"))
    r1 = single.fit(train_ds, test_ds)

    # the gang additionally runs CHAINED dispatch (lax.scan over stacked
    # batches assembled with make_array_from_process_local_data): matching
    # the unchained single-process run proves the chain is exact in the
    # multi-process path too
    gang = _estimator(ckpt_dir=str(tmp_path / "gang"), steps_per_dispatch=2)
    r2 = gang.fit_gang(train_ds, test_ds, num_workers=2, run_timeout=900.0)

    assert len(r2.history) == len(r1.history)
    np.testing.assert_allclose(
        [h["train_loss"] for h in r2.history],
        [h["train_loss"] for h in r1.history], rtol=2e-5)
    np.testing.assert_allclose(
        [h["eval_loss"] for h in r2.history],
        [h["eval_loss"] for h in r1.history], rtol=2e-5)

    k1 = np.asarray(single.get_model()["params"]["Dense_0"]["kernel"])
    k2 = np.asarray(gang.get_model()["params"]["Dense_0"]["kernel"])
    np.testing.assert_allclose(k2, k1, rtol=1e-4, atol=1e-5)


def test_gang_rank_failure_restarts_from_checkpoint(session, tmp_path):
    from raydp_tpu.data.dataset import from_frame

    flag = str(tmp_path / "crashed-once")

    def crash_once(report):
        # rank 1 dies mid-job exactly once; the gang must restart and resume
        import jax

        if (report["epoch"] == 1 and jax.process_index() == 1
                and not os.path.exists(flag)):
            open(flag, "w").close()
            os._exit(1)

    df = _linear_df(session, n=1024)
    ds = from_frame(df)
    est = _estimator(num_epochs=4, callbacks=[crash_once],
                     ckpt_dir=str(tmp_path / "ck"))
    result = est.fit_gang(ds, num_workers=2, max_retries=1,
                          run_timeout=900.0)
    assert os.path.exists(flag), "the injected crash never fired"
    # every epoch appears exactly once: the restarted gang resumed from the
    # checkpoint (no replays) and restored the pre-crash history (no holes)
    assert [h["epoch"] for h in result.history] == [0, 1, 2, 3]
    # the checkpoint sidecar proves the second incarnation did not re-train
    # from scratch: at least one pre-crash epoch came from the restore
    import raydp_tpu.train.checkpoint as ckpt
    assert ckpt.restore_extra(str(tmp_path / "ck"))["history"]


def test_gang_rejects_indivisible_batch():
    from raydp_tpu.data.feed import GangShardIterator

    class _FakeDs:
        def block_sizes(self):
            return [10, 10]

    with pytest.raises(ValueError, match="divisible"):
        GangShardIterator(_FakeDs(), global_batch=10, world_size=3, rank=0,
                          columns={"x": ("x", np.float32)})


def test_gang_iterator_covers_rows_exactly_once():
    """_runs boundary math: every global batch row is read exactly once per
    epoch, across uneven block boundaries and both ranks."""
    from raydp_tpu.data.feed import GangShardIterator

    sizes = [7, 13, 5, 22, 1]          # awkward block sizes, total 48
    rows = np.arange(48, dtype=np.float64)
    blocks = []
    start = 0
    for s in sizes:
        import pyarrow as pa
        blocks.append(pa.table({"x": rows[start:start + s]}))
        start += s

    class _Ds:
        def block_sizes(self):
            return sizes

        def get_block(self, i, zero_copy=False):
            return blocks[i]

    got = []
    for rank in (0, 1):
        it = GangShardIterator(_Ds(), global_batch=16, world_size=2,
                               rank=rank, columns={"x": ("x", np.float64)})
        assert len(it) == 3
        for batch in it:
            assert batch["x"].shape == (8,)
            got.extend(batch["x"].tolist())
    # 3 global batches x 16 rows = rows 0..47 exactly once across both ranks
    assert sorted(got) == list(range(48))


def test_gang_iterator_over_cap_decodes_slices_not_blocks(monkeypatch):
    """A block that exceeds the RDT_FEED_CACHE_MB budget is never decoded
    whole per batch: the iterator slices the Arrow table to the requested
    rows first, so over-cap feeds pay O(batch) decode work (advisor r4)."""
    import pyarrow as pa

    from raydp_tpu.data.feed import GangShardIterator

    rows = np.arange(64, dtype=np.float64)
    table = pa.table({"x": rows})
    log = []

    class _SpyTable:
        def slice(self, off, n):
            log.append(("slice", off, n))
            return table.slice(off, n)

        def column(self, c):
            log.append(("full-decode", c))
            return table.column(c)

    class _Ds:
        def block_sizes(self):
            return [64]

        def get_block(self, i, zero_copy=False):
            return _SpyTable()

    def run():
        log.clear()
        it = GangShardIterator(_Ds(), global_batch=16, world_size=2, rank=0,
                               columns={"x": ("x", np.float64)})
        out = [b["x"].copy() for b in it]
        return np.concatenate(out)

    monkeypatch.setenv("RDT_FEED_CACHE_MB", "0")   # block can never cache
    over = run()
    assert all(kind == "slice" for kind, *_ in log), log
    assert len(log) == 4                            # one slice per batch

    monkeypatch.setenv("RDT_FEED_CACHE_MB", "64")  # block caches on first use
    under = run()
    assert ("full-decode", "x") in log
    assert sum(1 for kind, *_ in log if kind == "full-decode") == 1
    np.testing.assert_array_equal(over, under)      # same rows either way
