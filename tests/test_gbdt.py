"""GBDT model + estimator tests (parity model: reference test_xgboost.py:31-57
— synthetic frames through fit_on_spark, prediction-shape checks; plus direct
algorithm quality assertions the reference leaves to xgboost upstream)."""

import numpy as np
import pandas as pd
import pytest

from raydp_tpu.models.gbdt import apply_bins, fit_gbdt, make_bins


def test_binning_roundtrip():
    rng = np.random.RandomState(0)
    X = rng.randn(1000, 3).astype(np.float32)
    edges = make_bins(X, num_bins=16)
    assert edges.shape == (3, 15)
    Xb = apply_bins(X, edges)
    assert Xb.min() >= 0 and Xb.max() <= 15
    # quantile bins are roughly balanced
    counts = np.bincount(Xb[:, 0], minlength=16)
    assert counts.min() > 20


def test_regression_quality():
    rng = np.random.RandomState(1)
    X = rng.rand(4000, 6).astype(np.float32)
    y = (2 * X[:, 0] - X[:, 1] ** 2 + np.sin(4 * X[:, 2])
         + 0.05 * rng.randn(4000)).astype(np.float32)
    model, _ = fit_gbdt(X, y, num_trees=40, max_depth=5, num_bins=64,
                        learning_rate=0.2)
    rmse = float(np.sqrt(np.mean((model.predict(X) - y) ** 2)))
    base = float(y.std())
    assert rmse < 0.2 * base, (rmse, base)


def test_classification_quality():
    rng = np.random.RandomState(2)
    X = rng.rand(3000, 4).astype(np.float32)
    y = (X[:, 0] + X[:, 1] > 1.0).astype(np.float32)
    model, _ = fit_gbdt(X, y, num_trees=30, max_depth=4, num_bins=64,
                        learning_rate=0.3, objective="binary:logistic")
    p = model.predict(X)
    assert ((p > 0.5) == (y > 0.5)).mean() > 0.97
    # probabilities, not margins
    assert 0.0 <= p.min() and p.max() <= 1.0
    margins = model.predict(X, output_margin=True)
    assert margins.min() < 0 or margins.max() > 1.0


def test_unsupported_objective():
    with pytest.raises(ValueError, match="objective"):
        fit_gbdt(np.zeros((10, 2), np.float32), np.zeros(10, np.float32),
                 objective="rank:pairwise")


def test_estimator_fit_on_frame(session):
    from raydp_tpu.train import GBDTEstimator

    rng = np.random.RandomState(3)
    x = rng.rand(600, 3).astype(np.float32)
    y = (x[:, 0] * 4 + x[:, 1] + 0.01 * rng.randn(600)).astype(np.float32)
    df = session.createDataFrame(
        pd.DataFrame({"f0": x[:, 0], "f1": x[:, 1], "f2": x[:, 2], "y": y}),
        num_partitions=2)
    train_df, eval_df = df.randomSplit([0.8, 0.2], seed=0)

    est = GBDTEstimator(
        params={"objective": "reg:squarederror", "max_depth": 4, "eta": 0.3,
                "max_bin": 64},
        feature_columns=["f0", "f1", "f2"], label_column="y",
        num_boost_round=30)
    result = est.fit_on_frame(train_df, eval_df)
    report = result.history[0]
    assert report["num_trees"] == 30
    assert report["train_rmse"] < 0.3
    assert "eval_rmse" in report

    model = est.get_model()
    preds = model.predict(x[:5])
    assert preds.shape == (5,)

    # checkpoint reload parity (per-iteration checkpoint keeping 1,
    # xgboost/estimator.py:60-68)
    loaded = GBDTEstimator.load_model(result.checkpoint_dir)
    np.testing.assert_allclose(loaded.predict(x[:5]), preds, rtol=1e-6)
