"""GBDT model + estimator tests (parity model: reference test_xgboost.py:31-57
— synthetic frames through fit_on_spark, prediction-shape checks; plus direct
algorithm quality assertions the reference leaves to xgboost upstream)."""

import numpy as np
import pandas as pd
import pytest

from raydp_tpu.models.gbdt import apply_bins, fit_gbdt, make_bins


def test_binning_roundtrip():
    rng = np.random.RandomState(0)
    X = rng.randn(1000, 3).astype(np.float32)
    edges = make_bins(X, num_bins=16)
    assert edges.shape == (3, 15)
    Xb = apply_bins(X, edges)
    assert Xb.min() >= 0 and Xb.max() <= 15
    # quantile bins are roughly balanced
    counts = np.bincount(Xb[:, 0], minlength=16)
    assert counts.min() > 20


def test_regression_quality():
    rng = np.random.RandomState(1)
    X = rng.rand(4000, 6).astype(np.float32)
    y = (2 * X[:, 0] - X[:, 1] ** 2 + np.sin(4 * X[:, 2])
         + 0.05 * rng.randn(4000)).astype(np.float32)
    model, _, _ = fit_gbdt(X, y, num_trees=40, max_depth=5, num_bins=64,
                        learning_rate=0.2)
    rmse = float(np.sqrt(np.mean((model.predict(X) - y) ** 2)))
    base = float(y.std())
    assert rmse < 0.2 * base, (rmse, base)


def test_classification_quality():
    rng = np.random.RandomState(2)
    X = rng.rand(3000, 4).astype(np.float32)
    y = (X[:, 0] + X[:, 1] > 1.0).astype(np.float32)
    model, _, _ = fit_gbdt(X, y, num_trees=30, max_depth=4, num_bins=64,
                        learning_rate=0.3, objective="binary:logistic")
    p = model.predict(X)
    assert ((p > 0.5) == (y > 0.5)).mean() > 0.97
    # probabilities, not margins
    assert 0.0 <= p.min() and p.max() <= 1.0
    margins = model.predict(X, output_margin=True)
    assert margins.min() < 0 or margins.max() > 1.0


def test_unsupported_objective():
    with pytest.raises(ValueError, match="objective"):
        fit_gbdt(np.zeros((10, 2), np.float32), np.zeros(10, np.float32),
                 objective="rank:pairwise")


def test_estimator_fit_on_frame(session):
    from raydp_tpu.train import GBDTEstimator

    rng = np.random.RandomState(3)
    x = rng.rand(600, 3).astype(np.float32)
    y = (x[:, 0] * 4 + x[:, 1] + 0.01 * rng.randn(600)).astype(np.float32)
    df = session.createDataFrame(
        pd.DataFrame({"f0": x[:, 0], "f1": x[:, 1], "f2": x[:, 2], "y": y}),
        num_partitions=2)
    train_df, eval_df = df.randomSplit([0.8, 0.2], seed=0)

    est = GBDTEstimator(
        params={"objective": "reg:squarederror", "max_depth": 4, "eta": 0.3,
                "max_bin": 64},
        feature_columns=["f0", "f1", "f2"], label_column="y",
        num_boost_round=30)
    result = est.fit_on_frame(train_df, eval_df)
    report = result.history[0]
    assert report["num_trees"] == 30
    assert report["train_rmse"] < 0.3
    assert "eval_rmse" in report

    model = est.get_model()
    preds = model.predict(x[:5])
    assert preds.shape == (5,)

    # checkpoint reload parity (per-iteration checkpoint keeping 1,
    # xgboost/estimator.py:60-68)
    loaded = GBDTEstimator.load_model(result.checkpoint_dir)
    np.testing.assert_allclose(loaded.predict(x[:5]), preds, rtol=1e-6)

    # estimator-level batched inference over a dataset (mirrors
    # FlaxEstimator.predict)
    from raydp_tpu.data import from_frame

    eval_ds = from_frame(eval_df)
    ds_preds = est.predict(eval_ds)
    assert ds_preds.shape == (eval_ds.count(),)
    exp = model.predict(np.stack(
        [eval_ds.to_arrow().column(c).to_numpy().astype(np.float32)
         for c in ["f0", "f1", "f2"]], axis=1))
    np.testing.assert_allclose(ds_preds, exp, rtol=1e-6)


def test_multiclass_matches_sklearn_quality():
    """multi:softprob on 4-class blobs: accuracy within 3 points of sklearn's
    GradientBoostingClassifier on the same data (VERDICT #8 done-bar)."""
    from sklearn.datasets import make_blobs
    from sklearn.ensemble import GradientBoostingClassifier

    X, y = make_blobs(n_samples=3000, centers=4, n_features=5,
                      cluster_std=3.0, random_state=3)
    X = X.astype(np.float32)
    cut = 2400
    model, _, _ = fit_gbdt(X[:cut], y[:cut].astype(np.float32),
                           num_trees=40, max_depth=4, num_bins=64,
                           learning_rate=0.2, objective="multi:softprob")
    probs = model.predict(X[cut:])
    assert probs.shape == (600, 4)
    np.testing.assert_allclose(probs.sum(axis=1), 1.0, rtol=1e-5)
    acc = float((probs.argmax(axis=1) == y[cut:]).mean())

    sk = GradientBoostingClassifier(n_estimators=40, max_depth=4,
                                    learning_rate=0.2, random_state=0)
    sk.fit(X[:cut], y[:cut])
    sk_acc = float(sk.score(X[cut:], y[cut:]))
    assert acc >= sk_acc - 0.03, (acc, sk_acc)

    # multi:softmax returns class ids directly
    model2, _, _ = fit_gbdt(X[:cut], y[:cut].astype(np.float32),
                            num_trees=10, max_depth=4, num_bins=64,
                            objective="multi:softmax")
    pred = model2.predict(X[cut:])
    assert set(np.unique(pred)).issubset({0.0, 1.0, 2.0, 3.0})


def test_per_round_eval_and_early_stopping():
    rng = np.random.RandomState(5)
    X = rng.rand(2000, 5).astype(np.float32)
    y = (X[:, 0] + 0.3 * rng.randn(2000)).astype(np.float32)  # noisy target
    cut = 1000
    model, _, evals = fit_gbdt(
        X[:cut], y[:cut], num_trees=200, max_depth=6, num_bins=64,
        learning_rate=0.5, evals=(X[cut:], y[cut:]),
        early_stopping_rounds=5)
    history = evals["eval_rmse"]
    # stopped early: deep greedy trees at lr=0.5 overfit noise quickly
    assert len(history) < 200
    assert model.best_iteration == int(np.argmin(history))
    # the forest is truncated to the best iteration
    assert model.num_trees == model.best_iteration + 1
    # per-round reporting really is per round
    assert len(history) == model.best_iteration + 1 + 5


def test_instance_weights_shift_the_fit():
    """Weighting duplicates: weight-2 fit == duplicated-row fit."""
    rng = np.random.RandomState(7)
    X = rng.rand(600, 3).astype(np.float32)
    y = (X[:, 0] > 0.5).astype(np.float32)
    w = np.where(y > 0, 2.0, 1.0).astype(np.float32)

    edges = make_bins(X, 32)
    m_w, _, _ = fit_gbdt(X, y, num_trees=10, max_depth=3, num_bins=32,
                         objective="binary:logistic", sample_weight=w,
                         bin_edges=edges)
    Xd = np.concatenate([X, X[y > 0]], axis=0)
    yd = np.concatenate([y, y[y > 0]], axis=0)
    m_d, _, _ = fit_gbdt(Xd, yd, num_trees=10, max_depth=3, num_bins=32,
                         objective="binary:logistic", bin_edges=edges)
    np.testing.assert_allclose(m_w.predict(X), m_d.predict(X),
                               rtol=1e-4, atol=1e-5)


def test_estimator_multiclass_early_stop(session):
    from raydp_tpu.train import GBDTEstimator

    rng = np.random.RandomState(11)
    n = 1500
    X = rng.rand(n, 4)
    label = (X[:, 0] * 3).astype(np.int64).clip(0, 2)
    pdf = pd.DataFrame({f"f{i}": X[:, i] for i in range(4)})
    pdf["y"] = label.astype(np.float64)
    df = session.createDataFrame(pdf, num_partitions=3)
    train_df, eval_df = df.randomSplit([0.8, 0.2], seed=0)

    est = GBDTEstimator(
        params={"objective": "multi:softprob", "num_class": 3,
                "max_depth": 3, "eta": 0.3},
        feature_columns=[f"f{i}" for i in range(4)],
        label_column="y", num_boost_round=60, early_stopping_rounds=8)
    result = est.fit_on_frame(train_df, eval_df)
    report = result.history[-1]
    assert report["eval_merror"] < 0.1
    assert "eval_mlogloss" in est.evals_result
    assert len(est.evals_result["eval_mlogloss"]) <= 60


def test_row_sharded_fit_matches_single_device():
    """mesh-sharded rows: XLA reduces the per-device partial histograms (the
    Rabit-allreduce slot); results must match the unsharded fit."""
    import jax

    from raydp_tpu.parallel import make_mesh

    rng = np.random.RandomState(9)
    n = 3001  # deliberately not divisible by 8: exercises zero-weight padding
    X = rng.rand(n, 5).astype(np.float32)
    y = (X[:, 0] - 2 * X[:, 1] + 0.1 * rng.randn(n)).astype(np.float32)

    edges = make_bins(X, 64)
    plain, pred_plain, _ = fit_gbdt(X, y, num_trees=12, max_depth=4,
                                    num_bins=64, bin_edges=edges)
    mesh = make_mesh()
    assert int(np.prod(list(mesh.shape.values()))) == 8
    shard, pred_shard, _ = fit_gbdt(X, y, num_trees=12, max_depth=4,
                                    num_bins=64, bin_edges=edges, mesh=mesh)
    assert pred_shard.shape == (n,)
    # reduction order can flip an argmax at a near-tied split, so require
    # near-identical structure (not bit-exact) plus matching predictions
    diff = np.mean(shard.split_feature != plain.split_feature)
    assert diff < 0.05, f"{diff:.1%} of split nodes differ"
    np.testing.assert_allclose(pred_shard, pred_plain, rtol=1e-3, atol=1e-4)


def test_fused_eval_scan_matches_host_loop():
    """The fused on-device train+eval scan (no early stopping: one dispatch
    for the whole history) must reproduce the host per-round loop's eval
    history and forest — the loop is the reference-semantics oracle (xgboost
    per-round eval reports, reference xgboost/estimator.py:54-81)."""
    rng = np.random.RandomState(3)
    X = rng.rand(2000, 5).astype(np.float32)
    y = (X[:, 0] - 2 * X[:, 1] + 0.1 * rng.randn(2000)).astype(np.float32)
    eX = rng.rand(400, 5).astype(np.float32)
    ey = (eX[:, 0] - 2 * eX[:, 1] + 0.1 * rng.randn(400)).astype(np.float32)

    kw = dict(num_trees=8, max_depth=4, num_bins=32, learning_rate=0.3,
              evals=(eX, ey))
    fused_model, fused_pred, fused_hist = fit_gbdt(X, y, **kw)
    # early_stopping_rounds > num_trees never fires: the host loop runs all
    # rounds and its history is the oracle trajectory
    host_model, host_pred, host_hist = fit_gbdt(
        X, y, early_stopping_rounds=kw["num_trees"] + 1, **kw)

    np.testing.assert_allclose(fused_hist["eval_rmse"],
                               host_hist["eval_rmse"][:8], rtol=1e-5)
    np.testing.assert_array_equal(fused_model.split_feature,
                                  host_model.split_feature)
    np.testing.assert_array_equal(fused_model.split_bin,
                                  host_model.split_bin)
    np.testing.assert_allclose(fused_model.leaf_value,
                               host_model.leaf_value, rtol=1e-5)
    np.testing.assert_allclose(fused_pred, host_pred, rtol=1e-4, atol=1e-5)


def test_fused_eval_scan_matches_host_loop_multiclass():
    """Multiclass twin of the fused-eval parity test: the vmapped K-tree
    round and the [K, nodes] eval routing must also match the host loop."""
    rng = np.random.RandomState(5)
    X = rng.rand(1500, 4).astype(np.float32)
    y = (X[:, 0] + X[:, 1] > 1.0).astype(np.float32) \
        + (X[:, 2] > 0.66).astype(np.float32)  # 3 classes
    eX = rng.rand(300, 4).astype(np.float32)
    ey = (eX[:, 0] + eX[:, 1] > 1.0).astype(np.float32) \
        + (eX[:, 2] > 0.66).astype(np.float32)

    kw = dict(num_trees=6, max_depth=3, num_bins=32, learning_rate=0.4,
              objective="multi:softmax", num_class=3, evals=(eX, ey))
    fused_model, _, fused_hist = fit_gbdt(X, y, **kw)
    host_model, _, host_hist = fit_gbdt(
        X, y, early_stopping_rounds=kw["num_trees"] + 1, **kw)

    np.testing.assert_allclose(fused_hist["eval_mlogloss"],
                               host_hist["eval_mlogloss"][:6], rtol=1e-5)
    np.testing.assert_array_equal(fused_model.split_feature,
                                  host_model.split_feature)
    np.testing.assert_allclose(fused_model.leaf_value,
                               host_model.leaf_value, rtol=1e-5)
