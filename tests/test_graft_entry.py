"""The driver's entry contract: single-chip compile + multi-chip dry run."""

import importlib.util
import os


def _load():
    path = os.path.join(os.path.dirname(__file__), "..", "__graft_entry__.py")
    spec = importlib.util.spec_from_file_location("__graft_entry__", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_entry_compiles():
    import jax

    mod = _load()
    fn, args = mod.entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)
    assert out.shape[0] == args[-1].shape[0]


def test_dryrun_multichip_8():
    mod = _load()
    mod.dryrun_multichip(8)


def test_dryrun_parity_catches_wrong_sharding(monkeypatch):
    """The dry run's parity gate must FAIL on a deliberately wrong sharding
    (a missed psum: loss averaged over the local batch shard only) — proof
    the allclose check detects wrong-but-finite numbers (VERDICT r3 #5)."""
    import pytest

    mod = _load()
    monkeypatch.setenv("RDT_DRYRUN_SABOTAGE", "1")
    with pytest.raises(RuntimeError, match="parity|diverges|Mismatch"):
        mod.dryrun_multichip(8)
