"""The driver's entry contract: single-chip compile + multi-chip dry run."""

import importlib.util
import os


def _load():
    path = os.path.join(os.path.dirname(__file__), "..", "__graft_entry__.py")
    spec = importlib.util.spec_from_file_location("__graft_entry__", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_entry_compiles():
    import jax

    mod = _load()
    fn, args = mod.entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)
    assert out.shape[0] == args[-1].shape[0]


def test_dryrun_multichip_8():
    mod = _load()
    mod.dryrun_multichip(8)
