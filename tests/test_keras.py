"""KerasEstimator tests (parity model: reference test_tf.py:33-82 — synthetic
linear-regression frames, fit_on_spark over both conversion paths, shape-only
model assertions)."""

import os

import numpy as np
import pandas as pd
import pytest

os.environ.setdefault("KERAS_BACKEND", "jax")


def _make_frame(session, n=512, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.rand(n, 2).astype(np.float32)
    y = (3.0 * x[:, 0] - 2.0 * x[:, 1] + 0.5
         + 0.01 * rng.randn(n)).astype(np.float32)
    pdf = pd.DataFrame({"a": x[:, 0], "b": x[:, 1], "y": y})
    return session.createDataFrame(pdf, num_partitions=2)


def _model():
    import keras

    return keras.Sequential([
        keras.layers.Input(shape=(2,)),
        keras.layers.Dense(16, activation="relu"),
        keras.layers.Dense(1),
    ])


def _estimator(**kw):
    from raydp_tpu.train import KerasEstimator

    defaults = dict(model=_model(), optimizer="adam", loss="mse",
                    metrics=["mae"], feature_columns=["a", "b"],
                    label_column="y", batch_size=64, num_epochs=4, seed=0)
    defaults.update(kw)
    return KerasEstimator(**defaults)


def test_fit_on_frame_object_store(session):
    df = _make_frame(session)
    train_df, eval_df = df.randomSplit([0.8, 0.2], seed=1)
    est = _estimator()
    result = est.fit_on_frame(train_df, eval_df)
    assert len(result.history) == 4
    assert result.history[-1]["loss"] < result.history[0]["loss"]
    assert "val_loss" in result.history[-1]
    model = est.get_model()
    preds = model.predict(np.array([[0.5, 0.5]], dtype=np.float32), verbose=0)
    assert preds.shape == (1, 1)


def test_fit_on_frame_parquet_spill(session, tmp_path):
    df = _make_frame(session)
    est = _estimator(num_epochs=2)
    result = est.fit_on_frame(df, fs_directory=str(tmp_path))
    assert len(result.history) == 2


def test_model_builder_and_spec_roundtrip(session):
    """The estimator stores a serialized spec, so the original model object is
    never mutated (parity: tf/estimator.py:96-149)."""
    df = _make_frame(session, n=256)
    est = _estimator(model=None, model_builder=_model, num_epochs=2)
    result = est.fit_on_frame(df)
    assert result.history
    # a second fit rebuilds from spec and works again
    result2 = est.fit_on_frame(df)
    assert result2.history


def test_data_parallel_over_virtual_mesh(session):
    """batch 64 over the 8 virtual CPU devices; DataParallel shards it 8×."""
    import jax

    assert len(jax.devices()) == 8
    df = _make_frame(session)
    est = _estimator(num_epochs=4, data_parallel=True)
    result = est.fit_on_frame(df)
    # the model must actually learn, not merely not diverge
    assert result.history[-1]["loss"] < result.history[0]["loss"]

    saved = os.path.join(result.checkpoint_dir, "model.keras")
    assert os.path.exists(saved)


def test_requires_model():
    from raydp_tpu.train import KerasEstimator

    with pytest.raises(ValueError, match="model"):
        KerasEstimator(feature_columns=["a"], label_column="y")


def test_keras_fit_gang_matches_single_process(session, tmp_path):
    """The gang path is a real peer of the Flax gang: 2 ranks under one
    global jax.distributed mesh must reproduce the single-process losses
    (same seed, same global batches) and leave a chief model.keras."""
    from raydp_tpu.data.dataset import from_frame

    df = _make_frame(session, n=1024)
    train_df, eval_df = df.randomSplit([0.8, 0.2], seed=1)
    train_ds, eval_ds = from_frame(train_df), from_frame(eval_df)

    single = _estimator(num_epochs=3, shuffle=False,
                        checkpoint_dir=str(tmp_path / "single"))
    r1 = single.fit(train_ds, eval_ds)

    # the gang additionally runs CHAINED dispatch: matching the unchained
    # single-process run proves the chain is exact multi-process too
    gang = _estimator(num_epochs=3, shuffle=False,
                      checkpoint_dir=str(tmp_path / "gang"),
                      steps_per_dispatch=2)
    r2 = gang.fit_gang(train_ds, eval_ds, num_workers=2, run_timeout=900.0)

    assert len(r2.history) == len(r1.history) == 3
    np.testing.assert_allclose([h["loss"] for h in r2.history],
                               [h["loss"] for h in r1.history], rtol=2e-4)
    np.testing.assert_allclose([h["val_loss"] for h in r2.history],
                               [h["val_loss"] for h in r1.history], rtol=2e-4)
    saved = os.path.join(r2.checkpoint_dir, "model.keras")
    assert os.path.exists(saved)
    model = gang.get_model()
    preds = model.predict(np.array([[0.5, 0.5]], dtype=np.float32), verbose=0)
    assert preds.shape == (1, 1)


def test_keras_steps_per_dispatch_chain_parity(session, monkeypatch):
    """Chained dispatch (lax.scan over k stacked batches) must produce the
    same loss history as per-batch dispatch — same update sequence, fewer
    host round trips (mirrors the FlaxEstimator chain-parity test)."""
    df = _make_frame(session, n=448)  # 7 batches of 64 → 7 % 3 != 0
    # pin the STREAMING feed — the resident path neither chains nor streams
    monkeypatch.setenv("RDT_DEVICE_CACHE", "0")

    def run(chain):
        from raydp_tpu.data import from_frame
        est = _estimator(num_epochs=2, shuffle=False,
                         steps_per_dispatch=chain)
        return est.fit(from_frame(df))

    plain = run(1)
    chained = run(3)
    for a, b in zip(plain.history, chained.history):
        np.testing.assert_allclose(a["loss"], b["loss"], rtol=1e-5, atol=1e-6)


def test_keras_device_cache_parity(session, monkeypatch):
    """The device-resident epoch path must walk exactly the streaming feed's
    update sequence at shuffle=False (mirrors the FlaxEstimator resident
    parity test, on the keras stateless loop)."""
    from raydp_tpu.data import from_frame

    df = _make_frame(session, n=448)
    eval_ds = from_frame(_make_frame(session, n=200, seed=1))
    monkeypatch.setenv("RDT_DEVICE_CACHE", "1")
    monkeypatch.delenv("RDT_DEVICE_CACHE_MB", raising=False)

    def run():
        est = _estimator(num_epochs=2, shuffle=False)
        return est.fit(from_frame(df), eval_ds)

    resident = run()
    assert all(r["feed_time_s"] == 0.0 for r in resident.history)
    monkeypatch.setenv("RDT_DEVICE_CACHE", "0")
    streamed = run()
    assert any(r["feed_time_s"] > 0.0 for r in streamed.history)
    for a, b in zip(resident.history, streamed.history):
        np.testing.assert_allclose(a["loss"], b["loss"], rtol=1e-5, atol=1e-6)
        # the resident eval scan must match the streaming eval pass
        np.testing.assert_allclose(a["val_loss"], b["val_loss"],
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(a["val_mean_absolute_error"],
                                   b["val_mean_absolute_error"],
                                   rtol=1e-5, atol=1e-6)


def test_fit_kwargs_path_interval_checkpoint(session, tmp_path, monkeypatch):
    """Custom fit_kwargs route through stock model.fit; the
    checkpoint_interval knob must hold there too (reference parity path,
    tf/estimator.py:171-210). A save spy pins the cadence — existence of the
    final archive alone cannot distinguish interval from save-every-epoch."""
    import os

    import keras

    saves = []
    real_save = keras.Model.save

    def spy(self, path, *a, **kw):
        saves.append(os.path.basename(str(path)))
        return real_save(self, path, *a, **kw)

    monkeypatch.setattr(keras.Model, "save", spy)

    df = _make_frame(session, n=256)
    ck = tmp_path / "ck"
    est = _estimator(num_epochs=3, fit_kwargs={"class_weight": None},
                     checkpoint_dir=str(ck), checkpoint_interval=5)
    result = est.fit_on_frame(df)
    assert len(result.history) == 3
    # interval 5 > 3 epochs: exactly ONE save — the forced final-epoch one
    assert saves == ["model.keras"]
    assert os.path.exists(ck / "model.keras")


@pytest.mark.slow
def test_keras_predict_matches_manual_apply(session):
    """predict() covers the full row count (ragged tail included) and agrees
    numerically with a manual get_model() + stateless_call apply — the flax
    twin's evidence standard (tests/test_train.py::test_estimator_predict)
    for the keras path (VERDICT r5 Weak #5: the method landed untested)."""
    import jax.numpy as jnp

    from raydp_tpu.data import from_frame

    df = _make_frame(session, n=300)  # 300 % 64 != 0: exercises the tail
    ds = from_frame(df)
    est = _estimator(num_epochs=2)
    est.fit(ds)

    preds = est.predict(ds)
    assert preds.shape == (300,) and preds.dtype == np.float32
    assert np.isfinite(preds).all()

    model = est.get_model()
    table = ds.to_arrow()
    x = np.stack([table.column("a").to_numpy(zero_copy_only=False),
                  table.column("b").to_numpy(zero_copy_only=False)],
                 axis=1).astype(np.float32)
    tv = [jnp.asarray(v) for v in model.trainable_variables]
    ntv = [jnp.asarray(v) for v in model.non_trainable_variables]
    manual, _ = model.stateless_call(tv, ntv, jnp.asarray(x), training=False)
    np.testing.assert_allclose(preds, np.asarray(manual).squeeze(-1),
                               rtol=1e-5, atol=1e-6)
    # predictions are real outputs, not a constant fill
    assert np.std(preds) > 0.0

    # a smaller explicit batch_size walks more batches, same answer
    np.testing.assert_array_equal(est.predict(ds, batch_size=50), preds)


@pytest.mark.slow
def test_keras_predict_labelless_frame(session):
    """The normal inference frame has NO label column: predict() only
    decodes feature columns, so it must work unchanged and return the same
    predictions as on the labeled frame."""
    from raydp_tpu.data import from_frame

    df = _make_frame(session, n=256)
    est = _estimator(num_epochs=2)
    est.fit(from_frame(df))

    preds = est.predict(from_frame(df))
    preds_nolabel = est.predict(from_frame(df.drop("y")))
    np.testing.assert_array_equal(preds_nolabel, preds)

    # before fit, predict must refuse loudly
    fresh = _estimator()
    with pytest.raises(RuntimeError, match="fit"):
        fresh.predict(from_frame(df))


def test_keras_batchnorm_resident(session):
    """BatchNorm (non-trainable running stats) threads through the resident
    epoch scan's carry — the bench's NYCTaxi-shaped keras model depends on
    it."""
    import keras

    def build():
        return keras.Sequential([
            keras.layers.Input(shape=(2,)),
            keras.layers.Dense(16, activation="relu"),
            keras.layers.BatchNormalization(),
            keras.layers.Dense(1),
        ])

    df = _make_frame(session, n=448)
    est = _estimator(model=None, model_builder=build, num_epochs=3)
    result = est.fit_on_frame(df)
    assert all(r["feed_time_s"] == 0.0 for r in result.history)
    assert result.history[-1]["loss"] < result.history[0]["loss"]
    # the running stats must have moved off their init (mean 0 / var 1)
    bn = [v for v in est.get_model().non_trainable_variables]
    moving_mean = np.asarray(bn[0])
    assert np.abs(moving_mean).max() > 1e-3
