"""Native host-feed staging kernel: output parity with the numpy decode path
across dtypes, chunking, offsets, and the fallback conditions.

The kernel (csrc/feed/stage.cpp via raydp_tpu/native/stage.py) replaces the
astype+np.stack double pass in ``feed._as_numpy``; these tests pin the two
paths byte-identical so the fast path can never silently change training
inputs."""

import numpy as np
import pyarrow as pa
import pytest

from raydp_tpu.native.stage import native_stage_available, stage_table


def _numpy_path(table, columns, dtype):
    return np.stack(
        [table.column(c).to_numpy(zero_copy_only=False).astype(dtype,
                                                               copy=False)
         for c in columns], axis=1)


needs_native = pytest.mark.skipif(not native_stage_available(),
                                  reason="native toolchain unavailable")


@needs_native
@pytest.mark.parametrize("dst", [np.float32, np.float64])
def test_stage_parity_mixed_source_dtypes(dst):
    rng = np.random.RandomState(0)
    table = pa.table({
        "f64": rng.randn(777),
        "f32": rng.randn(777).astype(np.float32),
        "i64": rng.randint(-1000, 1000, 777),
        "i32": rng.randint(-1000, 1000, 777).astype(np.int32),
        "u8": rng.randint(0, 255, 777).astype(np.uint8),
        "i16": rng.randint(-300, 300, 777).astype(np.int16),
    })
    cols = ["f64", "f32", "i64", "i32", "u8", "i16"]
    out = stage_table(table, cols, np.dtype(dst))
    assert out is not None and out.dtype == np.dtype(dst)
    np.testing.assert_array_equal(out, _numpy_path(table, cols, dst))


@needs_native
@pytest.mark.parametrize("dst", [np.int32, np.int64])
def test_stage_parity_int_sources_to_int(dst):
    """Integer→integer pairs stay on the kernel (float sources to an int dst
    are declined — see test_stage_declines_float_to_int_pairs)."""
    rng = np.random.RandomState(0)
    table = pa.table({
        "i64": rng.randint(-1000, 1000, 777),
        "i32": rng.randint(-1000, 1000, 777).astype(np.int32),
        "u8": rng.randint(0, 255, 777).astype(np.uint8),
        "i16": rng.randint(-300, 300, 777).astype(np.int16),
    })
    cols = ["i64", "i32", "u8", "i16"]
    out = stage_table(table, cols, np.dtype(dst))
    assert out is not None and out.dtype == np.dtype(dst)
    np.testing.assert_array_equal(out, _numpy_path(table, cols, dst))


@needs_native
def test_stage_parity_chunked_and_sliced():
    """Multi-chunk columns (uneven chunking per column) and non-zero array
    offsets (a sliced table) hit the per-chunk path."""
    a = np.arange(100, dtype=np.float64)
    b = np.arange(100, dtype=np.int64) * 3
    table = pa.table({
        "a": pa.chunked_array([a[:30], a[30:]]),
        "b": pa.chunked_array([b[:50], b[50:80], b[80:]]),
    })
    out = stage_table(table, ["a", "b"], np.dtype(np.float32))
    np.testing.assert_array_equal(
        out, _numpy_path(table, ["a", "b"], np.float32))

    sliced = table.slice(17, 41)   # chunks carry offsets now
    out = stage_table(sliced, ["a", "b"], np.dtype(np.float32))
    assert out is not None
    np.testing.assert_array_equal(
        out, _numpy_path(sliced, ["a", "b"], np.float32))


@needs_native
def test_stage_declines_ineligible_columns():
    withnull = pa.table({"a": pa.array([1.0, None, 3.0]),
                         "b": pa.array([1.0, 2.0, 3.0])})
    assert stage_table(withnull, ["a", "b"], np.dtype(np.float32)) is None

    strings = pa.table({"a": pa.array(["x", "y"]),
                        "b": pa.array([1.0, 2.0])})
    assert stage_table(strings, ["a", "b"], np.dtype(np.float32)) is None

    one = pa.table({"a": pa.array([1.0, 2.0])})
    assert stage_table(one, ["a"], np.dtype(np.float32)) is None  # numpy wins

    ints = pa.table({"a": pa.array([1, 2]), "b": pa.array([3, 4])})
    assert stage_table(ints, ["a", "b"], np.dtype(np.float16)) is None


@needs_native
def test_stage_declines_float_to_int_pairs():
    """ADVICE r5 #2: float→int static_cast is UB in C++ for NaN/out-of-range
    values while numpy's astype is (different) platform-defined behavior —
    the byte-parity contract cannot hold, so the kernel declines the pair
    and the feed silently falls back to numpy."""
    rng = np.random.RandomState(3)
    table = pa.table({"a": rng.randn(64), "b": rng.randn(64)})
    assert stage_table(table, ["a", "b"], np.dtype(np.int32)) is None
    assert stage_table(table, ["a", "b"], np.dtype(np.int64)) is None

    # one float source among ints declines the whole table (the numpy path
    # redoes the full decode anyway)
    mixed = pa.table({"a": pa.array([1.0, 2.0]), "b": pa.array([3, 4])})
    assert stage_table(mixed, ["a", "b"], np.dtype(np.int64)) is None

    # int→int and float→float pairs stay on the kernel
    ints = pa.table({"a": pa.array([1, 2]), "b": pa.array([3, 4])})
    assert stage_table(ints, ["a", "b"], np.dtype(np.int32)) is not None
    assert stage_table(table, ["a", "b"], np.dtype(np.float32)) is not None

    # the feed-level contract: _as_numpy still produces the numpy answer
    from raydp_tpu.data.feed import _as_numpy

    got = _as_numpy(table, ("a", "b"), np.int32)
    np.testing.assert_array_equal(
        got, _numpy_path(table, ["a", "b"], np.int32))


@needs_native
def test_stage_threads_parity(monkeypatch):
    monkeypatch.setenv("RDT_STAGE_THREADS", "3")
    rng = np.random.RandomState(1)
    table = pa.table({f"c{i}": rng.randn(501) for i in range(7)})
    cols = [f"c{i}" for i in range(7)]
    out = stage_table(table, cols, np.dtype(np.float32))
    np.testing.assert_array_equal(out, _numpy_path(table, cols, np.float32))


def test_as_numpy_uses_native_path_when_available():
    """feed._as_numpy output is identical whether or not the kernel engages
    (the integration contract: silent fallback, same bytes)."""
    from raydp_tpu.data.feed import _as_numpy

    rng = np.random.RandomState(2)
    table = pa.table({"x": rng.randn(64), "y": rng.randn(64),
                      "z": rng.randint(0, 9, 64)})
    got = _as_numpy(table, ("x", "y", "z"), np.float32)
    np.testing.assert_array_equal(
        got, _numpy_path(table, ["x", "y", "z"], np.float32))
    # single column keeps the 1-D contract
    assert _as_numpy(table, ("x",), np.float32).shape == (64,)
