"""Native (C++) object-store core: allocator invariants + store integration.

Parity model: the reference rides Ray's plasma store (native shared memory,
SURVEY.md §2.3 item 11); these tests cover our C++ arena the way the reference's
suite covers its data plane — real processes, real shared memory, fault paths
(test_spark_cluster.py:262-366 exercises cached-block recovery and GC).
"""

import multiprocessing as mp
import threading

import numpy as np
import pyarrow as pa
import pytest

from raydp_tpu.native.arena import Arena, native_store_available

pytestmark = pytest.mark.skipif(
    not native_store_available(), reason="native store core did not build")


@pytest.fixture
def arena():
    a = Arena.create(f"rdt-test-{mp.current_process().pid}", 8 << 20)
    yield a
    a.close()


def test_alloc_free_roundtrip(arena):
    off = arena.alloc(1000)
    assert off is not None and off % 64 == 0
    view = arena.view(off, 1000)
    view[:] = b"x" * 1000
    assert bytes(arena.view(off, 1000)) == b"x" * 1000
    stats = arena.stats()
    assert stats["num_allocs"] == 1
    assert stats["bytes_in_use"] >= 1000
    assert arena.free(off)
    stats = arena.stats()
    assert stats["num_allocs"] == 0
    assert stats["bytes_in_use"] == 0


def test_double_free_rejected(arena):
    off = arena.alloc(64)
    assert arena.free(off)
    assert not arena.free(off)


def test_bogus_free_rejected(arena):
    assert not arena.free(12345 + 3)  # unaligned garbage offset
    assert not arena.free(arena.size + 64)  # out of range


def test_split_and_coalesce(arena):
    # Allocate three adjacent blocks, free in an order that exercises both
    # predecessor and successor coalescing, then verify the space is reusable
    # as one large block.
    offs = [arena.alloc(4096) for _ in range(3)]
    assert all(o is not None for o in offs)
    baseline = arena.stats()["bytes_in_use"]
    assert baseline >= 3 * 4096
    arena.free(offs[1])
    arena.free(offs[0])  # coalesces with freed middle block
    arena.free(offs[2])  # coalesces with the merged front block
    assert arena.stats()["bytes_in_use"] == 0
    big = arena.alloc(3 * 4096 + 128)
    assert big is not None
    assert big == offs[0]  # space was merged back into one front block


def test_exhaustion_returns_none(arena):
    assert arena.alloc(64 << 20) is None  # larger than the 8 MiB arena
    offs = []
    while True:
        off = arena.alloc(1 << 20)
        if off is None:
            break
        offs.append(off)
    assert len(offs) >= 6  # 8 MiB arena, 1 MiB blocks, minus headers
    for off in offs:
        assert arena.free(off)
    assert arena.stats()["bytes_in_use"] == 0


def test_concurrent_alloc_free_threads(arena):
    errors = []

    def worker(seed):
        rng = np.random.RandomState(seed)
        try:
            for _ in range(200):
                size = int(rng.randint(1, 8192))
                off = arena.alloc(size)
                if off is None:
                    continue
                view = arena.view(off, size)
                view[:] = bytes([seed % 256]) * size
                if bytes(view) != bytes([seed % 256]) * size:
                    errors.append("corrupt payload")
                if not arena.free(off):
                    errors.append("free failed")
        except Exception as e:  # pragma: no cover
            errors.append(repr(e))

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    assert arena.stats()["num_allocs"] == 0


def _child_alloc(segment, out_q):
    a = Arena.attach(segment)
    off = a.alloc(512)
    a.view(off, 512)[:] = b"c" * 512
    out_q.put(off)
    a.detach()


def test_cross_process_alloc(arena):
    """A second process allocates from the same arena; the parent reads the
    payload zero-copy — the plasma-style multi-writer contract."""
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    p = ctx.Process(target=_child_alloc, args=(arena.segment, q))
    p.start()
    off = q.get(timeout=30)
    p.join(timeout=30)
    assert p.exitcode == 0
    assert bytes(arena.view(off, 512)) == b"c" * 512
    assert arena.stats()["num_allocs"] == 1
    assert arena.free(off)


def test_store_uses_arena(runtime):
    """Default (auto) mode: payloads land in the arena, free reclaims them,
    Arrow tables round-trip zero-copy."""
    client = runtime.store_client
    info = runtime.store_server.arena_info()
    assert info is not None, "native core built but arena not created"

    table = pa.table({"a": np.arange(1000), "b": np.random.rand(1000)})
    ref = client.put(table)
    seg, size, kind, offset, host_id, _ = runtime.store_server.lookup(ref.id)
    assert offset >= 0 and seg == info["segment"]
    got = client.get(ref)
    assert got.equals(table)

    before = runtime.store_server.arena_stats()["bytes_in_use"]
    assert before > 0
    view_table = client.get(ref, zero_copy=True)  # borrowed view of the arena
    client.free([ref])
    # reclamation is deferred for a grace period so borrowed zero-copy views
    # (device feed, lineage recovery) can't be overwritten under the reader
    assert runtime.store_server.arena_stats()["bytes_in_use"] == before
    assert view_table.equals(table)
    runtime.store_server.host._reap_deferred(everything=True)
    after = runtime.store_server.arena_stats()["bytes_in_use"]
    assert after < before


def test_store_survives_actor_writes(runtime):
    """An actor process writes through the arena; the driver reads it back."""
    class Writer:
        def put_table(self, n):
            from raydp_tpu.runtime.object_store import get_client
            t = pa.table({"x": np.arange(n, dtype=np.int64)})
            return get_client().put(t)

    handle = runtime.create_actor(Writer, name="arena-writer")
    ref = handle.call("put_table", 4096)
    seg, size, kind, offset, host_id, _ = runtime.store_server.lookup(ref.id)
    assert offset >= 0, "actor write did not use the arena"
    table = runtime.store_client.get(ref)
    assert table.num_rows == 4096
    assert table["x"][4095].as_py() == 4095


def test_store_native_off(monkeypatch):
    """Forced-off mode still round-trips through per-object segments."""
    from raydp_tpu import config as cfg
    from raydp_tpu.runtime import head as head_mod

    rt = head_mod.RuntimeContext(
        config=cfg.Config({cfg.NATIVE_OBJECT_STORE_KEY: "off"}))
    try:
        assert rt.store_server.arena_info() is None
        ref = rt.store_client.put({"k": 1})
        assert rt.store_client.get(ref) == {"k": 1}
        seg, size, kind, offset, host_id, _ = rt.store_server.lookup(ref.id)
        assert offset == -1
    finally:
        rt.shutdown()


def test_remote_store_client_roundtrip(runtime):
    """A store client in remote mode (a process on a node-agent machine that
    cannot map the head's shared memory) reads and writes payloads through
    the table server's fetch/store RPCs — the cross-host data plane."""
    from raydp_tpu.runtime.object_store import ObjectStoreClient

    local = runtime.store_client
    remote = ObjectStoreClient(runtime.store_server, runtime.session_id,
                               default_owner="remote-node", remote=True)

    # local write (arena fast path) → remote read via RPC bytes
    table = pa.table({"a": np.arange(500), "b": np.random.rand(500)})
    ref = local.put(table)
    got = remote.get(ref)
    assert got.equals(table)

    # remote write (server-mediated) → local zero-copy read
    ref2 = remote.put({"x": [1, 2, 3]})
    assert local.get(ref2) == {"x": [1, 2, 3]}
    t3 = pa.table({"c": np.arange(64, dtype=np.int64)})
    ref3 = remote.put(t3)
    assert local.get(ref3, zero_copy=True).equals(t3)
    # the remote write is owned by the remote actor: owner sweep reclaims it
    runtime.store_server.free_owned_by("remote-node")
    assert not local.contains(ref2)
