"""Node agents: real multi-node actor placement.

Parity: the substrate role Ray's raylets play for the reference (SURVEY.md §1
L1; ray_cluster_master.py:185-203 adopts real node addresses). Two agent
daemons join a head; node-affinity actors land in the agents' processes; a
killed agent reads as node death and its restartable actors reroute.
"""

import os
import signal
import subprocess
import sys
import time

import pytest


class Echo:
    def pids(self):
        return {"pid": os.getpid(), "ppid": os.getppid()}

    def get(self, x):
        return x


def _start_agent(head_url, cpus=4.0):
    env = dict(os.environ)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [sys.executable, "-m", "raydp_tpu.runtime.node_agent",
         "--head", head_url, "--cpus", str(cpus)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        start_new_session=True)
    return proc


def _wait_nodes(rt, n, timeout=30.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        alive = [x for x in rt.resource_manager.nodes() if x.alive]
        if len(alive) >= n:
            return alive
        time.sleep(0.2)
    raise TimeoutError(f"never saw {n} alive nodes")


def test_agents_join_and_affinity_placement(runtime):
    rt = runtime
    a1 = _start_agent(rt.server.url)
    a2 = _start_agent(rt.server.url)
    try:
        _wait_nodes(rt, 3)  # driver node + 2 agent nodes
        agent_nodes = sorted(rt.node_agents)
        assert len(agent_nodes) == 2

        # node-affinity: the actor must land in agent #2's process tree
        target = agent_nodes[1]
        h = runtime.create_actor(Echo, name="remote-echo", node_id=target,
                                 resources={"CPU": 1.0})
        info = h.pids()
        agent_pids = {a1.pid, a2.pid}
        assert info["ppid"] in agent_pids, (
            f"actor parent {info['ppid']} is not a node agent {agent_pids}")
        assert info["ppid"] != os.getpid()
        # and specifically the agent serving `target`
        listed = rt.node_agents[target].call("list_pids")
        assert info["pid"] in {int(p) for p in listed}
    finally:
        for p in (a1, a2):
            try:
                os.killpg(p.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                p.kill()


def test_agent_death_reroutes_restartable_actor(runtime):
    rt = runtime
    a1 = _start_agent(rt.server.url)
    try:
        _wait_nodes(rt, 2)
        (agent_node,) = list(rt.node_agents)
        h = runtime.create_actor(Echo, name="nomad", node_id=agent_node,
                                 resources={"CPU": 1.0}, max_restarts=-1)
        first = h.pids()
        assert first["ppid"] == a1.pid

        # node death: kill the agent (its children die with it)
        os.killpg(a1.pid, signal.SIGKILL)

        deadline = time.time() + 60.0
        second = None
        while time.time() < deadline:
            try:
                got = h.pids()
                if got["pid"] != first["pid"]:
                    second = got
                    break
            except Exception:
                pass
            time.sleep(0.5)
        assert second is not None, "actor never revived after agent death"
        # revived on the surviving (driver) node: parent is this process
        assert second["ppid"] == os.getpid()
        assert second["pid"] != first["pid"]
        # the dead agent's node is gone from the alive set
        node = rt.resource_manager.get_node(agent_node)
        assert node is None or not node.alive
        assert agent_node not in rt.node_agents
    finally:
        try:
            os.killpg(a1.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass


def test_spmd_ranks_spawn_on_agent_nodes(runtime):
    """A gang with SPREAD placement fans its ranks out across node agents —
    one rank process per machine, mpirun-hosts style."""
    from raydp_tpu.spmd import create_spmd_job

    rt = runtime
    a1 = _start_agent(rt.server.url)
    try:
        _wait_nodes(rt, 2)
        job = create_spmd_job("agent-gang", world_size=2,
                              placement_strategy="SPREAD")
        job.start()
        try:
            ppids = job.run(lambda ctx: os.getppid())
        finally:
            job.stop()
        assert a1.pid in ppids, (ppids, a1.pid)      # one rank on the agent
        assert os.getpid() in ppids                  # one rank local
    finally:
        try:
            os.killpg(a1.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
