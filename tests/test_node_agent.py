"""Node agents: real multi-node actor placement.

Parity: the substrate role Ray's raylets play for the reference (SURVEY.md §1
L1; ray_cluster_master.py:185-203 adopts real node addresses). Two agent
daemons join a head; node-affinity actors land in the agents' processes; a
killed agent reads as node death and its restartable actors reroute.
"""

import os
import signal
import subprocess
import sys
import time

import pytest


class Echo:
    def pids(self):
        return {"pid": os.getpid(), "ppid": os.getppid()}

    def get(self, x):
        return x


def _start_agent(head_url, cpus=4.0):
    env = dict(os.environ)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [sys.executable, "-m", "raydp_tpu.runtime.node_agent",
         "--head", head_url, "--cpus", str(cpus)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        start_new_session=True)
    return proc


def _wait_nodes(rt, n, timeout=30.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        alive = [x for x in rt.resource_manager.nodes() if x.alive]
        if len(alive) >= n:
            return alive
        time.sleep(0.2)
    raise TimeoutError(f"never saw {n} alive nodes")


def test_agents_join_and_affinity_placement(runtime):
    rt = runtime
    a1 = _start_agent(rt.server.url)
    a2 = _start_agent(rt.server.url)
    try:
        _wait_nodes(rt, 3)  # driver node + 2 agent nodes
        agent_nodes = sorted(rt.node_agents)
        assert len(agent_nodes) == 2

        # node-affinity: the actor must land in agent #2's process tree
        target = agent_nodes[1]
        h = runtime.create_actor(Echo, name="remote-echo", node_id=target,
                                 resources={"CPU": 1.0})
        info = h.pids()
        agent_pids = {a1.pid, a2.pid}
        assert info["ppid"] in agent_pids, (
            f"actor parent {info['ppid']} is not a node agent {agent_pids}")
        assert info["ppid"] != os.getpid()
        # and specifically the agent serving `target`
        listed = rt.node_agents[target].call("list_pids")
        assert info["pid"] in {int(p) for p in listed}
    finally:
        for p in (a1, a2):
            try:
                os.killpg(p.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                p.kill()


def test_agent_death_reroutes_restartable_actor(runtime):
    rt = runtime
    a1 = _start_agent(rt.server.url)
    try:
        _wait_nodes(rt, 2)
        (agent_node,) = list(rt.node_agents)
        h = runtime.create_actor(Echo, name="nomad", node_id=agent_node,
                                 resources={"CPU": 1.0}, max_restarts=-1)
        first = h.pids()
        assert first["ppid"] == a1.pid

        # node death: kill the agent (its children die with it)
        os.killpg(a1.pid, signal.SIGKILL)

        deadline = time.time() + 60.0
        second = None
        while time.time() < deadline:
            try:
                got = h.pids()
                if got["pid"] != first["pid"]:
                    second = got
                    break
            except Exception:
                pass
            time.sleep(0.5)
        assert second is not None, "actor never revived after agent death"
        # revived on the surviving (driver) node: parent is this process
        assert second["ppid"] == os.getpid()
        assert second["pid"] != first["pid"]
        # the dead agent's node is gone from the alive set
        node = rt.resource_manager.get_node(agent_node)
        assert node is None or not node.alive
        assert agent_node not in rt.node_agents
    finally:
        try:
            os.killpg(a1.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass


def test_fit_gang_trains_through_node_agent(session, tmp_path):
    """The full multi-node training path: a 2-rank FlaxEstimator gang where
    one rank spawns on a node agent (SPREAD placement) — the remote rank
    joins jax.distributed via the published coordinator and reads its data
    shard over the cross-host store RPC. Losses must match the local run."""
    import numpy as np
    import optax
    import pandas as pd

    from raydp_tpu.data.dataset import from_frame
    from raydp_tpu.models import MLP
    from raydp_tpu.runtime import get_runtime
    from raydp_tpu.train import FlaxEstimator

    rt = get_runtime()
    agent = _start_agent(rt.server.url, cpus=4.0)
    try:
        _wait_nodes(rt, 2)

        rng = np.random.RandomState(0)
        x = rng.random_sample((1024, 2))
        y = x @ np.array([2.0, -3.0]) + 1.0
        df = session.createDataFrame(
            pd.DataFrame({"x1": x[:, 0], "x2": x[:, 1], "y": y}),
            num_partitions=4)
        ds = from_frame(df)

        marker_dir = str(tmp_path)

        def record_parent(report):
            # runs inside every rank once per epoch: record who spawned us
            path = os.path.join(marker_dir, f"ppid-{os.getpid()}")
            with open(path, "w") as f:
                f.write(str(os.getppid()))

        def make_est(callbacks=None):
            return FlaxEstimator(
                model=MLP(features=(8,), use_batch_norm=False),
                optimizer=optax.sgd(5e-2), loss="mse",
                feature_columns=["x1", "x2"], label_column="y",
                batch_size=64, num_epochs=2, shuffle=False,
                callbacks=callbacks)

        r_local = make_est().fit(ds)
        r_gang = make_est([record_parent]).fit_gang(ds, num_workers=2,
                                                    run_timeout=900.0)

        np.testing.assert_allclose(
            [h["train_loss"] for h in r_gang.history],
            [h["train_loss"] for h in r_local.history], rtol=2e-4)
        # one rank ran under the agent, one locally (SPREAD over 2 nodes)
        ppids = {int(open(os.path.join(marker_dir, f)).read())
                 for f in os.listdir(marker_dir) if f.startswith("ppid-")}
        assert agent.pid in ppids, (ppids, agent.pid)
        assert os.getpid() in ppids
    finally:
        try:
            os.killpg(agent.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass


class SlowStart:
    """Actor whose __init__ stalls — the seeded stand-in for the jax/pyarrow
    import storm a fresh executor pays on a cold node."""

    def __init__(self, delay_s: float = 3.0):
        time.sleep(delay_s)

    def pid(self) -> int:
        return os.getpid()

    def put_marker(self, owner: str) -> str:
        import pyarrow as pa

        from raydp_tpu.runtime.object_store import get_client
        ref = get_client().put_arrow(pa.table({"a": [1]}), owner=owner)
        return ref.id


def test_spawn_then_reap_roundtrip_under_slow_warmup(runtime):
    """ISSUE 13 satellite: the scale-up/scale-down round trip through a
    node agent. Spawn-side: a seeded slow actor __init__ (the import-storm
    model) is absorbed by the RDT_EXECUTOR_WAIT_S readiness probe — the
    actor is admitted only once genuinely ready. Reap-side: the kill goes
    through the head to the agent, the agent's ``reap`` RPC harvests the
    process-table entry, and neither an orphan process nor the dead owner's
    store entries survive."""
    from raydp_tpu import knobs
    from raydp_tpu.runtime.object_store import ObjectRef

    rt = runtime
    agent_proc = _start_agent(rt.server.url)
    try:
        _wait_nodes(rt, 2)
        (agent_node,) = list(rt.node_agents)

        warmup = 3.0
        t0 = time.monotonic()
        h = rt.create_actor(SlowStart, (warmup,), name="slow-warmup",
                            node_id=agent_node, resources={"CPU": 1.0},
                            max_restarts=0, block=False)
        h.wait_ready(timeout=float(knobs.get("RDT_EXECUTOR_WAIT_S")))
        assert time.monotonic() - t0 >= warmup, (
            "readiness reported before the warm-up finished")

        pid = h.pid()
        listed = rt.node_agents[agent_node].call("list_pids")
        assert pid in {int(p) for p in listed}
        oid = h.put_marker("slow-warmup")
        assert rt.store_client.contains(ObjectRef(id=oid))

        # reap: deliberate kill through the head (the agent kills the
        # process group), then the agent-side table harvest
        h.kill(no_restart=True)
        agent = rt.node_agents[agent_node]
        code = None
        deadline = time.time() + 30
        while time.time() < deadline:
            code = agent.call("reap", pid)
            if code is not None:
                break
            time.sleep(0.2)
        assert code is not None, "reaped process never exited"
        # no orphan: the process is gone AND its table entry harvested
        assert not os.path.exists(f"/proc/{pid}")
        assert pid not in {int(p) for p in agent.call("list_pids")}
        # the dead owner's store entries are swept by the head
        deadline = time.time() + 30
        while time.time() < deadline \
                and rt.store_client.contains(ObjectRef(id=oid)):
            time.sleep(0.2)
        assert not rt.store_client.contains(ObjectRef(id=oid)), (
            "reap left the dead actor's store entries behind")
    finally:
        try:
            os.killpg(agent_proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass


def test_spmd_ranks_spawn_on_agent_nodes(runtime):
    """A gang with SPREAD placement fans its ranks out across node agents —
    one rank process per machine, mpirun-hosts style."""
    from raydp_tpu.spmd import create_spmd_job

    rt = runtime
    a1 = _start_agent(rt.server.url)
    try:
        _wait_nodes(rt, 2)
        job = create_spmd_job("agent-gang", world_size=2,
                              placement_strategy="SPREAD")
        job.start()
        try:
            ppids = job.run(lambda ctx: os.getppid())
        finally:
            job.stop()
        assert a1.pid in ppids, (ppids, a1.pid)      # one rank on the agent
        assert os.getpid() in ppids                  # one rank local
    finally:
        try:
            os.killpg(a1.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
