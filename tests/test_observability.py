"""Observability plane (ISSUE 12): causal cross-process tracing, the typed
metrics registry, and the failure flight recorder.

Units pin the registry contracts (typed increments, bounded rings that
announce truncation, snapshot merging, prometheus rendering) and the
context-propagation contract across every hard handoff: the RPC dispatcher,
DeferredReply completions on worker threads, executor streaming-task
threads, the serve dispatcher→worker→hedge chain, speculation (loser links
to the same parent, winner flagged), and a legacy caller without trace
metadata. Integration tests run a real 2-executor session: cross-process
flow events in the merged chrome trace, metrics_report() subsuming
op_counts(), skipped-actor accounting, and the blackbox bundle a
chaos-failed action writes.
"""

import collections
import json
import os
import threading
import time
from concurrent.futures import Future
from types import SimpleNamespace

import numpy as np
import pyarrow as pa
import pytest

import raydp_tpu
from raydp_tpu import metrics, profiler
from raydp_tpu.etl.engine import ExecutorPool
from raydp_tpu.runtime import rpc as rpc_mod
from raydp_tpu.runtime.rpc import (
    ConnectionLost, DeferredReply, RpcClient, RpcServer,
)


@pytest.fixture(autouse=True)
def _clean_telemetry():
    metrics.reset()
    profiler.clear()
    yield
    metrics.reset()
    profiler.clear()


# ---------------------------------------------------------------------------
# metrics registry units
# ---------------------------------------------------------------------------

def test_metrics_registry_is_typed():
    metrics.inc("serve_requests_total")
    metrics.inc("serve_requests_total", 2)
    metrics.set_gauge("serve_queue_depth", 7)
    metrics.observe("serve_request_seconds", 0.25)
    metrics.observe("serve_request_seconds", 0.75)
    metrics.inc("store_ops_total", label="seal")
    snap = metrics.snapshot()
    assert snap["counters"]["serve_requests_total"][""] == 3
    assert snap["counters"]["store_ops_total"]["seal"] == 1
    assert snap["gauges"]["serve_queue_depth"][""] == 7
    h = snap["hists"]["serve_request_seconds"][""]
    assert h["count"] == 2 and h["min"] == 0.25 and h["max"] == 0.75
    with pytest.raises(KeyError):
        # rdtlint: allow[telemetry-registry] deliberate unregistered-name probe
        metrics.inc("nope_total")
    with pytest.raises(ValueError):
        # rdtlint: allow[telemetry-registry] deliberate kind-mismatch probe
        metrics.inc("serve_request_seconds")  # histogram via counter API
    with pytest.raises(KeyError):
        # rdtlint: allow[telemetry-registry] deliberate unregistered-kind probe
        metrics.record_event("nope_event")


def test_event_ring_bounded_and_drop_counted(monkeypatch):
    monkeypatch.setenv("RDT_FLIGHT_MAX_EVENTS", "16")
    for i in range(20):
        metrics.record_event("hedge", dispatch=i)
    evs = metrics.events()
    assert len(evs) == 16
    assert evs[0]["dispatch"] == 4  # oldest four evicted
    snap = metrics.snapshot()
    assert snap["counters"]["flightrec_events_dropped_total"][""] == 4
    state = metrics.export_state()
    assert state["events_dropped"] == 4 and len(state["events"]) == 16


def test_merge_snapshots_sums_and_folds_hists():
    a = {"counters": {"serve_requests_total": {"": 2}},
         "gauges": {"serve_queue_depth": {"": 1}},
         "hists": {"serve_request_seconds":
                   {"": {"count": 2, "sum": 1.0, "min": 0.2, "max": 0.8}}}}
    b = {"counters": {"serve_requests_total": {"": 3},
                      "store_ops_total": {"seal": 1}},
         "gauges": {"serve_queue_depth": {"": 2}},
         "hists": {"serve_request_seconds":
                   {"": {"count": 1, "sum": 0.1, "min": 0.1, "max": 0.1}}}}
    m = metrics.merge_snapshots([a, b])
    assert m["counters"]["serve_requests_total"][""] == 5
    assert m["counters"]["store_ops_total"]["seal"] == 1
    assert m["gauges"]["serve_queue_depth"][""] == 3
    h = m["hists"]["serve_request_seconds"][""]
    assert h == {"count": 3, "sum": 1.1, "min": 0.1, "max": 0.8}


def test_prometheus_rendering():
    metrics.inc("store_ops_total", label="seal")
    metrics.observe("train_epoch_seconds", 1.5)
    text = metrics.render_prometheus(
        metrics.metrics_report(include_actors=False)["merged"])
    assert 'rdt_store_ops_total{op="seal"} 1' in text
    assert "# TYPE rdt_store_ops_total counter" in text
    assert "rdt_train_epoch_seconds_count 1" in text
    assert "rdt_train_epoch_seconds_max 1.5" in text


def test_dump_writes_json_and_prom(tmp_path):
    metrics.inc("serve_requests_total")
    paths = metrics.dump(str(tmp_path))
    report = json.loads(open(paths["json"]).read())
    assert report["merged"]["counters"]["serve_requests_total"][""] == 1
    assert "rdt_serve_requests_total 1" in open(paths["prom"]).read()


# ---------------------------------------------------------------------------
# profiler units: parentage, stable tids, drop accounting
# ---------------------------------------------------------------------------

def test_trace_nesting_records_parentage():
    with profiler.trace("etl:action", "driver", action="t"):
        outer = profiler.capture()
        with profiler.trace("stage:run", "etl"):
            inner = profiler.capture()
    assert profiler.capture() is None  # context fully unwound
    by_name = {s["name"]: s for s in profiler.spans()}
    act, stage = by_name["etl:action"], by_name["stage:run"]
    assert outer == (act["tr"], act["sid"])
    assert inner == (stage["tr"], stage["sid"])
    assert stage["tr"] == act["tr"] and stage["par"] == act["sid"]
    assert "par" not in act  # the root minted the trace


def test_sibling_top_level_spans_mint_distinct_traces():
    with profiler.trace("etl:action", "driver"):
        pass
    with profiler.trace("etl:action", "driver"):
        pass
    trs = [s["tr"] for s in profiler.spans()]
    assert len(set(trs)) == 2


def test_open_close_span_is_idempotent_and_contextual():
    with profiler.trace("etl:action", "driver"):
        span = profiler.open_span("serve:predict", "serve", rows=3)
    profiler.close_span(span)
    profiler.close_span(span)  # second close: no double record
    recs = [s for s in profiler.spans() if s["name"] == "serve:predict"]
    assert len(recs) == 1
    act = [s for s in profiler.spans() if s["name"] == "etl:action"][0]
    assert recs[0]["par"] == act["sid"]
    assert profiler.span_context(span) == (recs[0]["tr"], recs[0]["sid"])


def test_stable_tids_and_thread_names():
    names = {}

    def worker():
        with profiler.trace("stage:run", "etl"):
            pass

    t = threading.Thread(target=worker, name="rdt-test-worker")
    t.start()
    t.join()
    with profiler.trace("stage:run", "etl"):
        pass
    tids = {s["tid"] for s in profiler.spans()}
    assert len(tids) == 2 and all(isinstance(t, int) for t in tids)
    names = profiler.thread_names()
    assert "rdt-test-worker" in names.values()


def test_span_ring_drop_is_counted(monkeypatch):
    monkeypatch.setattr(profiler, "_spans",
                        collections.deque(maxlen=2))
    for _ in range(3):
        with profiler.trace("stage:run", "etl"):
            pass
    assert len(profiler.spans()) == 2
    assert profiler.spans_dropped() == 1
    snap = metrics.snapshot()
    assert snap["counters"]["profiler_spans_dropped_total"][""] == 1
    assert profiler.export_spans()["dropped"] == 1


def test_set_enabled_false_suppresses_open_spans_too():
    """Review fix: the async open/close pair honors the disable contract
    exactly like trace() — a disabled profiler records NOTHING from the
    serving plane."""
    profiler.set_enabled(False)
    try:
        span = profiler.open_span("serve:predict", "serve", rows=1)
        assert profiler.span_context(span) is None
        profiler.close_span(span)
        with profiler.trace("etl:action", "driver"):
            pass
        assert profiler.spans() == []
    finally:
        profiler.set_enabled(True)


def test_recycled_thread_ident_gets_fresh_lane():
    """Review fix: the OS recycling a dead thread's ident for a different
    thread must not render the new thread's spans under the dead thread's
    name."""
    ident = threading.get_ident()
    with profiler._tid_lock:
        old_tid = profiler._tids.get(ident)
        old_name = profiler._tid_names.get(old_tid) if old_tid else None
        profiler._tids[ident] = 999_999
        profiler._tid_names[999_999] = "rdt-dead-thread"
    try:
        tid = profiler._stable_tid()
        assert tid != 999_999
        assert profiler.thread_names()[tid] \
            == threading.current_thread().name
    finally:
        with profiler._tid_lock:
            profiler._tid_names.pop(999_999, None)
            if old_tid is not None:
                profiler._tids[ident] = old_tid
                profiler._tid_names[old_tid] = old_name


def test_clock_offset_midpoint():
    # a peer 5 ms ahead of us must measure ~+5000 µs
    off = profiler.measure_clock_offset(
        lambda: time.time_ns() + 5_000_000, samples=3)
    assert 4000 < off < 6000


# ---------------------------------------------------------------------------
# RPC propagation: dispatcher install, DeferredReply handoff, legacy caller
# ---------------------------------------------------------------------------

def _rpc_pair(handler):
    server = RpcServer(handler, name="obs-test")
    client = RpcClient(server.address)
    return server, client


def test_rpc_dispatch_installs_caller_context():
    seen = {}

    def handler(method, args, kwargs):
        if method == "ping":
            return "pong"
        seen["ctx"] = profiler.capture()
        with profiler.trace("stage:run", "etl"):
            pass
        return True

    server, client = _rpc_pair(handler)
    try:
        with profiler.trace("etl:action", "driver"):
            driver_ctx = profiler.capture()
            client.call("telemetry", timeout=10.0)
        assert seen["ctx"] == driver_ctx
        remote = [s for s in profiler.spans() if s["name"] == "stage:run"][0]
        assert remote["tr"] == driver_ctx[0]
        assert remote["par"] == driver_ctx[1]
    finally:
        client.close()
        server.stop()


def test_rpc_deferred_reply_worker_thread_keeps_context():
    """The streaming-task shape: the handler enqueues to a worker thread
    and returns a DeferredReply — the span the worker records must still
    parent to the caller's span."""

    def handler(method, args, kwargs):
        if method == "ping":
            return "pong"
        fut: Future = Future()
        ctx = profiler.capture()  # dispatcher thread: caller context live

        def work():
            with profiler.activate(ctx):
                with profiler.trace("task:", "executor"):
                    pass
                fut.set_result(profiler.capture())

        threading.Thread(target=work, daemon=True).start()
        return DeferredReply(fut)

    server, client = _rpc_pair(handler)
    try:
        with profiler.trace("stage:run", "etl"):
            driver_ctx = profiler.capture()
            worker_ctx = client.call("telemetry", timeout=10.0)
        assert worker_ctx == driver_ctx
        task = [s for s in profiler.spans() if s["name"] == "task:"][0]
        assert task["par"] == driver_ctx[1]
    finally:
        client.close()
        server.stop()


def test_legacy_caller_without_metadata_dispatches_cleanly():
    """A 4-tuple request (a peer running pre-causal code) must dispatch
    exactly as before, with no installed context."""
    seen = {}

    def handler(method, args, kwargs):
        seen["ctx"] = profiler.capture()
        return ("ok", args, kwargs)

    server = RpcServer(handler, name="obs-legacy")
    try:
        import socket

        import cloudpickle
        sock = socket.create_connection(server.address, timeout=10.0)
        lock = threading.Lock()
        rpc_mod._send_frame(
            sock, cloudpickle.dumps((7, "work", (1,), {"k": 2})), lock)
        req_id, ok, value = cloudpickle.loads(rpc_mod._recv_frame(sock))
        assert (req_id, ok) == (7, True)
        assert value == ("ok", (1,), {"k": 2})
        assert seen["ctx"] is None
        sock.close()
    finally:
        server.stop()


def test_rpc_without_active_trace_sends_no_metadata():
    """No active trace → the wire payload stays the legacy 4-tuple (byte
    compatibility with old peers is symmetric)."""
    captured = {}
    orig = rpc_mod.cloudpickle.dumps

    def spy(obj):
        if isinstance(obj, tuple) and len(obj) in (4, 5) \
                and isinstance(obj[0], int):
            captured.setdefault("lens", []).append(len(obj))
        return orig(obj)

    def handler(method, args, kwargs):
        return "pong"

    server = RpcServer(handler, name="obs-plain")
    client = RpcClient(server.address)
    try:
        rpc_mod.cloudpickle.dumps = spy
        client.call("ping", timeout=10.0)
        with profiler.trace("etl:action", "driver"):
            client.call("ping", timeout=10.0)
    finally:
        rpc_mod.cloudpickle.dumps = orig
        client.close()
        server.stop()
    assert 4 in captured["lens"] and 5 in captured["lens"]


# ---------------------------------------------------------------------------
# speculation: both attempts share the parent, the winner is flagged
# ---------------------------------------------------------------------------

class _CtxStub:
    """Executor-handle stand-in recording the trace context active at each
    submit (what the RPC client would ship) — the driver-side propagation
    contract for speculative pairs."""

    def __init__(self, name, latency=0.01):
        self.name = name
        self.latency = latency
        self.ctxs = []
        self._lock = threading.Lock()

    def submit(self, method, payload):
        with self._lock:
            self.ctxs.append(profiler.capture())
        fut: Future = Future()
        threading.Timer(self.latency, lambda: fut.set_result(
            {"num_rows": 1, "executor": self.name})).start()
        return fut

    def drop_blocks(self, keys, if_stamp=None):
        pass


def test_speculation_attempts_share_parent_and_winner_flagged(monkeypatch):
    monkeypatch.setenv("RDT_SPECULATION_MIN_S", "0.05")
    monkeypatch.setenv("RDT_SPECULATION_QUANTILE", "0.5")
    slow = _CtxStub("slow", latency=2.0)
    fast = _CtxStub("fast", latency=0.01)
    pool = ExecutorPool([slow, fast])
    tasks = [SimpleNamespace(task_id=f"t{i}") for i in range(4)]
    stats = {}
    with profiler.trace("stage:run", "etl"):
        stage_ctx = profiler.capture()
        out = pool.run_tasks(tasks, payloads=[b"p"] * 4, sched_stats=stats)
    assert stats["speculation_won"] >= 1
    # the winner result is flagged; the loser is the same task's other copy
    assert sum(int(r.get("_speculation_won", 0)) for r in out) \
        == stats["speculation_won"]
    # EVERY attempt — originals, backups (winners AND losers-to-be) — was
    # submitted under the same stage span: the loser's remote span would
    # link to the same parent as the winner's
    for ctx in slow.ctxs + fast.ctxs:
        assert ctx == stage_ctx
    snap = metrics.snapshot()
    assert snap["counters"]["sched_speculation_won_total"][""] \
        == stats["speculation_won"]
    assert sum(snap["counters"]["sched_tasks_dispatched_total"].values()) \
        == len(slow.ctxs) + len(fast.ctxs)


# ---------------------------------------------------------------------------
# serve dispatcher → worker → hedge propagation (fake replicas)
# ---------------------------------------------------------------------------

class _CtxReplica:
    """FakeReplicaHandle twin recording the context active at each
    serve_predict submit."""

    def __init__(self, name, delay_s=0.0):
        self.name = name
        self.delay_s = delay_s
        self.ctxs = []
        self._lock = threading.Lock()

    def call(self, method, *args, timeout=None, **kwargs):
        if method in ("serve_load", "serve_unload"):
            return {"replica": args[0]} if method == "serve_load" else True
        raise AssertionError(method)

    def submit(self, method, *args, **kwargs):
        fut: Future = Future()
        if method == "serve_load":
            fut.set_result({"replica": args[0]})
            return fut
        assert method == "serve_predict"
        with self._lock:
            self.ctxs.append(profiler.capture())
        _rid, payload = args

        def _serve():
            if self.delay_s:
                time.sleep(self.delay_s)
            table = pa.ipc.open_stream(pa.py_buffer(payload)).read_all()
            v = table.column("v").to_numpy(zero_copy_only=False)
            fut.set_result((v * 2.0).astype(np.float32))

        threading.Thread(target=_serve, daemon=True).start()
        return fut


def test_serve_dispatch_and_hedge_share_request_trace(monkeypatch):
    from raydp_tpu.serve import ServingSession

    monkeypatch.setenv("RDT_SERVE_MAX_BATCH", "1000")
    monkeypatch.setenv("RDT_SERVE_BATCH_TIMEOUT_MS", "5.0")
    monkeypatch.setenv("RDT_SERVE_HEDGE", "1")
    monkeypatch.setenv("RDT_SERVE_HEDGE_QUANTILE", "0.5")
    monkeypatch.setenv("RDT_SERVE_HEDGE_MULTIPLIER", "1.5")
    monkeypatch.setenv("RDT_SERVE_HEDGE_MIN_MS", "40.0")
    monkeypatch.setenv("RDT_SERVE_REROUTE_GRACE_S", "10.0")
    slow = _CtxReplica("slow")
    fast = _CtxReplica("fast")
    srv = ServingSession("/nonexistent/bundle",
                         executors=[slow, fast], name="obs")
    def _span_index():
        spans = profiler.spans()
        return ({s["sid"] for s in spans if s["name"] == "serve:predict"},
                {s["sid"]: s for s in spans
                 if s["name"] in ("serve:batch", "serve:hedge")})

    try:
        # warm the hedge deadline window with fast round trips
        for _ in range(10):
            srv.predict({"v": np.asarray([1.0])}, timeout=10.0)
        # every dispatch submit ran under a serve:batch span whose parent
        # is some request's serve:predict span — the full causal chain
        predict_sids, dispatch_spans = _span_index()
        for ctx in slow.ctxs + fast.ctxs:
            assert ctx is not None
            sp = dispatch_spans[ctx[1]]
            assert sp["par"] in predict_sids and sp["tr"] == ctx[0]
        # now a slow attempt: the hedge fires and BOTH attempts (the loser
        # included) link to the SAME serve:predict parent; the hedge copy
        # is flagged by its serve:hedge span name
        slow.delay_s = 0.5
        fast.delay_s = 0.5
        ns, nf = len(slow.ctxs), len(fast.ctxs)
        srv.predict({"v": np.asarray([3.0])}, timeout=10.0)
        rep = srv.serving_report()
        assert rep["hedged"] >= 1
        new = slow.ctxs[ns:] + fast.ctxs[nf:]
        assert len(new) >= 2
        _, dispatch_spans = _span_index()
        pair = [dispatch_spans[c[1]] for c in new]
        assert len({s["par"] for s in pair}) == 1  # same request parent
        assert len({s["tr"] for s in pair}) == 1   # same trace
        assert {s["name"] for s in pair} == {"serve:batch", "serve:hedge"}
        snap = metrics.snapshot()
        assert snap["counters"]["serve_hedged_total"][""] >= 1
        assert snap["counters"]["serve_requests_total"][""] == 11
        assert snap["hists"]["serve_batch_occupancy_rows"][""]["count"] \
            == rep["batches"]
        # review fix: the gauge drains back to 0 once the session idles
        # (each dispatcher loop pass refreshes it) instead of freezing at
        # the last pre-dispatch depth. Poll: the hedge LOSER is legitimately
        # still in flight when predict() returns with the winner
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            srv.serving_report()  # round-trips (and ticks) the loop
            if metrics.snapshot()["gauges"]["serve_queue_depth"]["obs"] \
                    == 0:
                break
            time.sleep(0.05)
        assert metrics.snapshot()["gauges"]["serve_queue_depth"]["obs"] == 0
    finally:
        srv.close(unload=False)


# ---------------------------------------------------------------------------
# executor streaming-task thread handoff (unit: the capture/activate shape)
# ---------------------------------------------------------------------------

def test_streaming_task_thread_adopts_dispatcher_context():
    """EtlExecutor.run_task hands the dispatcher's context to the dedicated
    streaming-task thread; this pins the module-level contract the executor
    uses (capture before Thread, activate inside)."""
    from raydp_tpu.etl import executor as ex_mod

    captured = {}

    class _Task:
        task_id = "t0"

    def fake_stream_sources_of(task):
        return ["stream"]

    class _FakeExec:
        _actor_name = "stub"

        def _run_task_obj(self, task):
            captured["ctx"] = profiler.capture()
            return {"num_rows": 0}

    import cloudpickle
    orig = ex_mod.T.stream_sources_of
    ex_mod.T.stream_sources_of = fake_stream_sources_of
    try:
        with profiler.trace("stage:run", "etl"):
            ctx = profiler.capture()
            reply = ex_mod.EtlExecutor.run_task(
                _FakeExec(), cloudpickle.dumps(_Task()))
        assert isinstance(reply, DeferredReply)
        assert reply.future.result(timeout=10.0) == {"num_rows": 0}
        assert captured["ctx"] == ctx
    finally:
        ex_mod.T.stream_sources_of = orig


# ---------------------------------------------------------------------------
# integration: real 2-executor session
# ---------------------------------------------------------------------------

def _groupagg(session, rows=2000):
    import pandas as pd
    df = session.createDataFrame(pd.DataFrame(
        {"k": np.arange(rows) % 7, "v": np.arange(float(rows))}))
    return df.groupBy("k").sum("v").collect()


def test_collect_chrome_trace_has_causal_flows(session, tmp_path):
    assert len(_groupagg(session)) == 7
    path = profiler.collect_chrome_trace(str(tmp_path / "trace.json"))
    assert path.skipped_actors == 0 and path.actors >= 2
    data = json.load(open(path))
    evs = data["traceEvents"]
    spans = [e for e in evs if e.get("ph") == "X"]
    # (i) >=1 cross-process flow event links a driver span to an executor
    # task span
    flows = [e for e in evs if e.get("cat") == "flow"]
    assert path.flow_events == len(flows) >= 2
    by_sid = {e["sid"]: e for e in spans if "sid" in e}
    finishes = [e for e in flows if e["ph"] == "f"]
    assert any(e["pid"] != 0 for e in finishes)
    starts = {e["id"] for e in flows if e["ph"] == "s"}
    assert all(e["id"] in starts for e in finishes)  # pairs, not orphans
    # executor task spans live in the driver action's trace
    actions = [s for s in spans if s["name"] == "etl:action"]
    task_spans = [s for s in spans
                  if str(s["name"]).startswith("task:") and s["pid"] != 0]
    assert actions and task_spans
    trs = {a["tr"] for a in actions}
    assert any(t["tr"] in trs for t in task_spans)
    # named thread lanes + collection health metadata
    assert any(e.get("name") == "thread_name" for e in evs)
    other = data["otherData"]
    assert other["skipped_actors"] == 0
    assert set(other["clock_offsets_us"]) >= {
        r["executor"] for r in []} | set(path.clock_offsets_us)
    assert "driver" in other["spans_dropped"]


def test_recovery_rerun_links_into_failed_actions_trace(monkeypatch,
                                                        tmp_path):
    """(ii) of the trace-smoke contract, in-process: after a seeded
    post-seal drop (armed via RDT_FAULTS so the EXECUTOR processes inherit
    it), the recovery span and the re-run's executor task spans carry the
    SAME trace id as the action that hit the loss."""
    import pandas as pd

    sentinel = str(tmp_path / "drop.sentinel")
    monkeypatch.setenv("RDT_FAULTS",
                       f"shuffle.write:drop:nth=1:once={sentinel}")
    s = raydp_tpu.init("obs-rec", num_executors=2, executor_cores=1,
                       executor_memory="512MB")
    try:
        df = s.createDataFrame(pd.DataFrame(
            {"k": np.arange(1000) % 5, "v": np.arange(1000.0)}))
        out = df.groupBy("k").sum("v").collect()
        assert len(out) == 5
        rep = [e for e in s.engine.shuffle_stage_report()
               if e["regenerated"]]
        assert rep, "seeded drop did not trigger recovery"
        path = profiler.collect_chrome_trace(str(tmp_path / "rec.json"))
    finally:
        raydp_tpu.stop()
    spans = [e for e in json.load(open(path))["traceEvents"]
             if e.get("ph") == "X"]
    recov = [s_ for s_ in spans if s_["name"] == "recover:lineage"]
    assert recov
    tr = recov[0]["tr"]
    actions = [s_ for s_ in spans if s_["name"] == "etl:action"
               and s_["tr"] == tr]
    assert actions, "recovery span lost its action's trace id"
    rerun_tasks = [s_ for s_ in spans if str(s_["name"]).startswith("task:")
                   and s_["pid"] != 0 and s_["tr"] == tr
                   and s_["ts"] >= recov[0]["ts"]]
    assert rerun_tasks, "no re-run executor task span in the action's trace"


def test_skipped_actor_is_counted_not_silent(session, tmp_path):
    from raydp_tpu.runtime import head as head_mod
    from raydp_tpu.runtime.actor import ALIVE, ActorSpec
    from raydp_tpu.runtime.head import ActorRecord

    rt = head_mod.get_runtime()
    ghost = ActorRecord(
        spec=ActorSpec(actor_id="ghost", name="ghost-actor",
                       cls_bytes=b"", args_bytes=b""),
        state=ALIVE, address=("127.0.0.1", 1))  # nothing listens there
    rt.records["ghost"] = ghost
    try:
        path = profiler.collect_chrome_trace(str(tmp_path / "t.json"))
        assert path.skipped_actors >= 1
        assert json.load(open(path))["otherData"]["skipped_actors"] >= 1
        rep = metrics.metrics_report()
        assert rep["skipped_processes"] >= 1
        assert "ghost-actor" not in rep["processes"]
        merged = rep["merged"]["counters"]
        assert merged["telemetry_skipped_processes_total"][""] >= 2
    finally:
        rt.records.pop("ghost", None)


def test_metrics_report_subsumes_op_counts(session):
    from raydp_tpu.runtime import head as head_mod

    _groupagg(session)
    rep = metrics.metrics_report()
    ops = rep["merged"]["counters"]["store_ops_total"]
    legacy = head_mod.get_runtime().store_server.op_counts()
    assert sum(ops.values()) == sum(legacy.values()) > 0
    for op, n in legacy.items():
        assert ops.get(op) == n
    # scheduler counters present and plausible
    dispatched = rep["merged"]["counters"]["sched_tasks_dispatched_total"]
    assert sum(dispatched.values()) > 0


def test_blackbox_bundle_on_chaos_failed_action(monkeypatch, tmp_path):
    """A chaos schedule that defeats recovery must leave a postmortem: the
    bundle carries the injected-fault events (executor processes), the
    object-loss events, and the driver's recovery rounds."""
    monkeypatch.setenv("RDT_FAULTS", "shuffle.write:drop:every=1")
    monkeypatch.setenv("RDT_LINEAGE_ROUNDS", "1")
    import pandas as pd

    from raydp_tpu.etl.engine import StageError
    from raydp_tpu.runtime import head as head_mod

    s = raydp_tpu.init("obs-chaos", num_executors=2, executor_cores=1,
                       executor_memory="512MB")
    try:
        session_dir = head_mod.get_runtime().session_dir
        df = s.createDataFrame(pd.DataFrame(
            {"k": np.arange(500) % 5, "v": np.arange(500.0)}))
        with pytest.raises(StageError):
            df.groupBy("k").sum("v").collect()
        bb_dir = os.path.join(session_dir, "blackbox")
        bundles = [f for f in os.listdir(bb_dir)
                   if f.startswith("blackbox-") and f.endswith(".json")]
        assert bundles, "failed action wrote no blackbox bundle"
        bundle = json.load(open(os.path.join(bb_dir, sorted(bundles)[0])))
        assert bundle["exc_type"] in ("ObjectsLostError", "StageError")
        kinds = {ev["kind"] for st in bundle["processes"].values()
                 for ev in st.get("events", [])}
        assert "fault_injected" in kinds, kinds
        assert "object_lost" in kinds, kinds
        assert "recovery_round" in kinds, kinds
        assert "action_failed" in kinds, kinds
        assert bundle["skipped_processes"] == 0
    finally:
        raydp_tpu.stop()
