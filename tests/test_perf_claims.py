"""README perf claims ↔ recorded artifacts consistency (VERDICT r4 #8).

The r2–r4 failure mode: README's "Measured performance" table carried
numbers (417k samples/s, 59.6% MFU, ...) that existed in NO recorded
artifact — claims and record drifted apart for three rounds. The contract
enforced here:

- ``PERF_CLAIMS.json`` maps every README perf number to a dotted path inside
  a recorded artifact in the tree (driver ``BENCH_r*.json`` — the bench line
  lives in their ``parsed``/``tail`` fields — or the bench-written
  ``BENCH_DETAIL.json``), with a tolerance.
- Every claim's artifact value must match the claimed value.
- Every claim's exact README string must appear in README.md.
- Every perf-looking number inside README's "Measured performance" section
  must be covered by some claim string — adding an unbacked number to the
  table fails this test.
"""

import json
import os
import re

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_artifact(name):
    path = os.path.join(ROOT, name)
    with open(path) as fh:
        data = json.load(fh)
    if "metric" in data:
        return data                      # a bare bench record
    if isinstance(data.get("parsed"), dict):
        return data["parsed"]            # driver wrapper, parsed line
    for line in reversed(data.get("tail", "").splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                j = json.loads(line)
                if "metric" in j:
                    return j
            except ValueError:
                continue
    raise AssertionError(f"{name}: no bench record found")


def _resolve(record, dotted):
    cur = record
    for part in dotted.split("."):
        if isinstance(cur, list):
            cur = cur[int(part)]
        else:
            assert isinstance(cur, dict) and part in cur, \
                f"path {dotted!r}: {part!r} missing"
            cur = cur[part]
    return cur


def _claims():
    path = os.path.join(ROOT, "PERF_CLAIMS.json")
    if not os.path.exists(path):
        pytest.skip("PERF_CLAIMS.json not present")
    with open(path) as fh:
        return json.load(fh)["claims"]


def _readme_perf_section():
    with open(os.path.join(ROOT, "README.md")) as fh:
        text = fh.read()
    m = re.search(r"## Measured performance.*?(?=\n## )", text, re.S)
    assert m, "README lost its Measured performance section"
    return text, m.group(0)


def _artifact_value(claim):
    if "regex" in claim:   # text artifacts (e.g. BASELINE.md tables)
        with open(os.path.join(ROOT, claim["artifact"])) as fh:
            m = re.search(claim["regex"], fh.read(), re.S)
        assert m, f"{claim['id']}: regex found nothing in {claim['artifact']}"
        return float(m.group(1).replace(",", "").replace("_", ""))
    return _resolve(_load_artifact(claim["artifact"]), claim["path"])


def _display_number(readme):
    """First number in the claim's README string, k/M-scaled."""
    m = re.search(r"([0-9][\d,]*(?:\.\d+)?)\s*(k|M)?", readme)
    num = float(m.group(1).replace(",", ""))
    return num * {None: 1.0, "k": 1e3, "M": 1e6}[m.group(2)]


def test_claims_match_artifacts():
    for claim in _claims():
        actual = _artifact_value(claim)
        expect = claim["value"]
        tol = claim.get("tol", 0.02)
        assert actual == pytest.approx(expect, rel=tol), \
            f"{claim['id']}: artifact {claim['artifact']} = {actual}, " \
            f"claim says {expect}"
        # and the HUMAN-VISIBLE number must round to the artifact value too
        # (a claim displaying 417k against a 133k artifact value would
        # otherwise pass on a sloppy 'value' field)
        shown = _display_number(claim["readme"])
        factor = claim.get("display_factor", 1.0)
        assert shown == pytest.approx(expect * factor, rel=0.05), \
            f"{claim['id']}: README shows {shown}, artifact holds " \
            f"{expect * factor}"


def test_readme_contains_every_claim_string():
    text, _ = _readme_perf_section()
    for claim in _claims():
        assert claim["readme"] in text, \
            f"{claim['id']}: README no longer contains {claim['readme']!r}"


#: the whole-tree claims fence (VERDICT r5 #3: ROOFLINE_LM.md's "measured
#: 59.6% MFU" lived outside the README-only fence for a full round). Every
#: file here is scanned for EXPLICIT measurement claims — "measured <number>
#: <perf unit>" — and each must be covered by a PERF_CLAIMS entry. Numbers
#: phrased as predictions/estimates are exempt: the fence forces the
#: prediction-vs-record distinction the r2–r4 drift erased.
MEASURED_CLAIM_FILES = [
    "benchmarks/ROOFLINE_LM.md",
    "benchmarks/gang_collective_microbench.py",
    "benchmarks/host_decode_bench.py",
    "benchmarks/shuffle_bench.py",
    "benchmarks/serve_bench.py",
    "bench.py",
    "doc/training.md",
    "doc/etl.md",
    "doc/serving.md",
    "README.md",
]

_MEASURED_RE = re.compile(
    # "measured", then up to 100 same-sentence chars (single line wraps
    # allowed — this repo's prose is 72-col wrapped — but not blank lines or
    # periods), then a number with a perf unit (MFU / tok/s / samples/s /
    # ms/step)
    r"measured(?:[^.\n]|\n(?!\n)){0,100}?"
    r"([0-9][\d,.]*\s*(?:k|M)?\s*(?:%?\s*MFU|tok/s|tokens/s"
    r"|samples/s(?:/chip)?|ms/step|×\s*fewer\s+shuffled\s+bytes"
    r"|×\s*fewer\s+store\s+metadata\s+RPCs"
    r"|×\s*fewer\s+reduce\s+dispatches"
    r"|×\s*faster\s+stage\s+wall"
    r"|×\s*lower\s+p99(?:\s+latency)?))",
    re.I)


def _claim_artifact_tokens(claims, name):
    """Numbers a claim's own regex pins INSIDE this file: the file IS the
    recorded artifact for them (e.g. the psum microbench docstring), so the
    fence accepts them verbatim."""
    out = []
    for c in claims:
        if c.get("artifact") == name and "regex" in c:
            with open(os.path.join(ROOT, name)) as fh:
                m = re.search(c["regex"], fh.read(), re.S)
            if m:
                out.append(m.group(1))
    return out


def test_tree_measured_claims_are_backed():
    """'measured <number> <unit>' anywhere in MEASURED_CLAIM_FILES must map
    to a PERF_CLAIMS entry — the README fence extended to a file list, so a
    measurement claim can no longer hide in a benchmark doc or docstring."""
    # positive control: the pattern must catch the r5 straggler's exact
    # phrasing (incl. a line wrap) — the fence can never go vacuous silently
    assert _MEASURED_RE.search("and measured **59.6% MFU / 83.3k\n"
                               "tok/s at T=8192** (v5e)")
    claims = _claims()
    covered = [c["readme"] for c in claims]
    for name in MEASURED_CLAIM_FILES:
        with open(os.path.join(ROOT, name)) as fh:
            text = fh.read()
        backed_here = _claim_artifact_tokens(claims, name)
        for m in _MEASURED_RE.finditer(text):
            token = m.group(1).strip()
            ok = (any(token in c for c in covered)
                  or any(t in token or token in t for t in backed_here))
            assert ok, (
                f"{name}: explicit measurement claim {m.group(0)!r} is not "
                "backed by any PERF_CLAIMS.json entry — record an artifact "
                "and add a claim, or rewrite it as a prediction")


def test_readme_perf_numbers_are_all_backed():
    """Every perf-shaped number in the Measured performance section must be
    part of some claim's README string (so new numbers need new claims)."""
    claims = _claims()
    _, section = _readme_perf_section()
    covered = [c["readme"] for c in claims]
    pattern = re.compile(
        r"[0-9][\d,.]*\s*(?:k|M)?\s*"
        # bare × only counts as a perf multiple when NOT a dimension product
        # ("18.2× torch-CPU" yes; "dim 512 × 4 layers" no); hyphenated and
        # of-peak percent spellings count too ("60%-MFU", "51% of peak")
        r"(?:samples/s(?:/chip)?|tok/s|tokens/s|TFLOP/s|%[ -]MFU|% of peak"
        r"|×(?!\s*\d)|ms\b)",
    )
    for match in pattern.finditer(section):
        token = match.group(0)
        assert any(token in c for c in covered), \
            f"README perf number {token!r} is not backed by any claim in " \
            "PERF_CLAIMS.json"
