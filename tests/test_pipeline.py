"""Pipeline parallelism (GPipe over the ``stage`` mesh axis) correctness:
the pipelined schedule must compute exactly what sequential layer application
computes — forward and gradients — including composed with a data axis and
with real transformer blocks as stages."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raydp_tpu.parallel import MeshSpec, make_mesh, pipeline_apply, \
    stack_stage_params

N_STAGES = 4
N_MICRO = 6
MB, DIM = 4, 16


def _stage_fn(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def _stage_params(seed):
    rng = np.random.RandomState(seed)
    return {"w": jnp.asarray(rng.normal(0, 0.5, (DIM, DIM)), jnp.float32),
            "b": jnp.asarray(rng.normal(0, 0.1, (DIM,)), jnp.float32)}


def _sequential(stacked, x_micro):
    def one(x):
        for i in range(N_STAGES):
            x = _stage_fn(jax.tree.map(lambda p: p[i], stacked), x)
        return x
    return jax.vmap(one)(x_micro)


@pytest.fixture
def stacked():
    return stack_stage_params([_stage_params(i) for i in range(N_STAGES)])


@pytest.fixture
def x_micro():
    rng = np.random.RandomState(42)
    return jnp.asarray(rng.normal(size=(N_MICRO, MB, DIM)), jnp.float32)


def test_pipeline_matches_sequential(stacked, x_micro):
    mesh = make_mesh(MeshSpec(stage=N_STAGES))
    got = pipeline_apply(_stage_fn, stacked, x_micro, mesh)
    ref = _sequential(stacked, x_micro)
    assert got.shape == ref.shape
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=1e-6, rtol=1e-6)


def test_pipeline_grads_match_sequential(stacked, x_micro):
    """AD through scan+ppermute IS the reverse pipeline: gradients w.r.t.
    every stage's params match the sequential model's."""
    mesh = make_mesh(MeshSpec(stage=N_STAGES))

    def loss_pp(p):
        return jnp.sum(pipeline_apply(_stage_fn, p, x_micro, mesh) ** 2)

    def loss_seq(p):
        return jnp.sum(_sequential(p, x_micro) ** 2)

    g_pp = jax.grad(loss_pp)(stacked)
    g_seq = jax.grad(loss_seq)(stacked)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-5),
        g_pp, g_seq)


def test_pipeline_composes_with_data_axis(stacked, x_micro):
    """pp x dp: stage=4 by data=2 on the 8-device mesh; microbatches sharded
    over data on their batch dim still produce the sequential answer."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = make_mesh(MeshSpec(stage=N_STAGES, data=2))
    xs = jax.device_put(x_micro, NamedSharding(mesh, P(None, "data")))
    got = pipeline_apply(_stage_fn, stacked, xs, mesh)
    ref = _sequential(stacked, x_micro)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=1e-6, rtol=1e-6)


def test_pipeline_multiple_layers_per_stage(x_micro):
    """8 stacked layers over 4 stages: each stage applies its contiguous pair
    in order — must equal plain sequential application of all 8."""
    n_layers = 8
    stacked8 = stack_stage_params([_stage_params(i) for i in range(n_layers)])
    mesh = make_mesh(MeshSpec(stage=N_STAGES))
    got = pipeline_apply(_stage_fn, stacked8, x_micro, mesh)

    def one(x):
        for i in range(n_layers):
            x = _stage_fn(jax.tree.map(lambda p: p[i], stacked8), x)
        return x
    ref = jax.vmap(one)(x_micro)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=1e-6, rtol=1e-6)


def test_pipeline_rejects_indivisible_layer_count(x_micro):
    mesh = make_mesh(MeshSpec(stage=N_STAGES))
    bad = stack_stage_params([_stage_params(i) for i in range(N_STAGES + 1)])
    with pytest.raises(ValueError, match="must divide"):
        pipeline_apply(_stage_fn, bad, x_micro, mesh)


def test_pipeline_no_stage_axis_is_sequential(stacked, x_micro):
    mesh = make_mesh(MeshSpec())      # stage=1
    got = pipeline_apply(_stage_fn, stacked, x_micro, mesh)
    ref = _sequential(stacked, x_micro)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=1e-6, rtol=1e-6)


def test_pipeline_transformer_blocks():
    """Real transformer Blocks as stages (dense attention, shape-uniform):
    2-stage pipeline over 8 devices vs the same blocks applied in order."""
    from raydp_tpu.models.transformer import Block

    dim, heads, t, mb, n_micro, n_stages = 32, 2, 16, 2, 3, 2
    mesh = make_mesh(MeshSpec(stage=n_stages))
    block = Block(num_heads=heads, attention="dense")
    rng = np.random.RandomState(0)
    x_micro = jnp.asarray(rng.normal(size=(n_micro, mb, t, dim)) * 0.3,
                          jnp.float32)

    stage_trees = [
        block.init(jax.random.PRNGKey(i), x_micro[0])["params"]
        for i in range(n_stages)
    ]
    stacked = stack_stage_params(stage_trees)

    def fn(params, x):
        return block.apply({"params": params}, x)

    got = pipeline_apply(fn, stacked, x_micro, mesh)

    def one(x):
        for tree in stage_trees:
            x = fn(tree, x)
        return x
    ref = jax.vmap(one)(x_micro)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
