"""End-to-end pipeline-parallel training (ISSUE 20): stage-stacked
estimator placement, unified microbatching, and per-role remat.

The contract under test: ``FlaxEstimator.fit`` on a mesh with ``stage > 1``
places a :class:`PipelineModel`'s layer stack across the ``stage`` axis and
runs the GPipe schedule as ONE compiled SPMD program — the ``accum_steps``
microbatches double as the pipeline microbatches, so a staged run must
reproduce the unstaged losses to tolerance (sharding is a layout, not a
math change). Misconfigurations (layers that do not divide over stages, a
monolithic model on a staged mesh, microbatches that do not divide the
batch, an unknown remat role/mode) must fail loudly BEFORE compile. The
chaos leg proves the staged state checkpoints and resumes bit-identically
through an injected epoch crash.

All legs run on the conftest 8-device CPU mesh (tier-1 safe).
"""

import flax.linen as nn
import numpy as np
import pandas as pd
import pytest

from raydp_tpu import faults, metrics
from raydp_tpu.parallel import make_mesh
from raydp_tpu.train import FlaxEstimator, PipelineModel

DIM = 8
FEATURES = [f"f{i}" for i in range(DIM)]


class Block(nn.Module):
    """Residual tanh block: cheap, yet deep enough to stack into stages."""

    @nn.compact
    def __call__(self, x):
        return x + nn.tanh(nn.Dense(DIM)(x))


def _model(n_layers=4):
    return PipelineModel(layers=[Block() for _ in range(n_layers)],
                        head=nn.Dense(1))


def _linear_ds(session, n=256, parts=4):
    from raydp_tpu.data.dataset import from_frame

    rng = np.random.RandomState(0)
    x = rng.normal(size=(n, DIM))
    w = rng.normal(size=(DIM,))
    pdf = pd.DataFrame({f"f{i}": x[:, i] for i in range(DIM)})
    pdf["label"] = x @ w + 0.1 * rng.normal(size=n)
    return from_frame(session.createDataFrame(pdf, num_partitions=parts))


def _est(**kw):
    kw.setdefault("model", _model())
    kw.setdefault("num_epochs", 3)
    return FlaxEstimator(loss="mse", feature_columns=FEATURES,
                         label_column="label", batch_size=64, seed=0,
                         shuffle=False, **kw)


def _losses(result):
    return [h["train_loss"] for h in result.history]


def _gauge(name):
    return metrics.snapshot()["gauges"].get(name, {}).get("")


def test_stage2_matches_stage1_losses_and_params(session):
    """The tentpole equivalence: a 2-stage pipelined fit (4 microbatches
    marching through the GPipe scan) reproduces the unstaged per-epoch
    losses AND the final parameters — the stage axis changes where layers
    live, never what they compute."""
    ds = _linear_ds(session)
    r1 = _est(mesh=make_mesh(dict(stage=1, data=8)), accum_steps=4).fit(ds)
    r2 = _est(mesh=make_mesh(dict(stage=2, data=4)), accum_steps=4).fit(ds)
    np.testing.assert_allclose(_losses(r2), _losses(r1), rtol=5e-4)
    import jax

    a = jax.tree_util.tree_leaves(r1.state.params)
    b = jax.tree_util.tree_leaves(r2.state.params)
    assert len(a) == len(b) and len(a) > 0
    for la, lb in zip(a, b):
        np.testing.assert_allclose(np.asarray(la), np.asarray(lb),
                                   atol=1e-5)


def test_unified_microbatching_accum_is_pipeline_microbatch(session):
    """accum_steps IS the pipeline microbatch count: different accum
    values at stage=2 land the same losses (row-weighted masked stats keep
    microbatch size out of the math), and the estimator reports the staged
    geometry through the train_pipeline_stages / train_accum_steps
    gauges."""
    ds = _linear_ds(session)
    base = _losses(_est(mesh=make_mesh(dict(stage=1, data=8))).fit(ds))
    for accum in (2, 4):
        r = _est(mesh=make_mesh(dict(stage=2, data=4)),
                 accum_steps=accum).fit(ds)
        np.testing.assert_allclose(_losses(r), base, rtol=5e-4,
                                   err_msg=f"accum={accum}")
        assert _gauge("train_pipeline_stages") == 2
        assert _gauge("train_accum_steps") == accum


def test_per_role_remat_policy_trains_to_same_loss(session):
    """A role→mode remat policy is a schedule hint, not a math change:
    checkpointing kernels at ``dots`` and everything else at ``full``
    lands the same losses as no remat at all."""
    ds = _linear_ds(session)
    base = _losses(_est(mesh=make_mesh(dict(stage=2, data=4)),
                        accum_steps=4).fit(ds))
    r = _est(mesh=make_mesh(dict(stage=2, data=4)), accum_steps=4,
             remat="embedding=none,kernel=dots,default=full").fit(ds)
    np.testing.assert_allclose(_losses(r), base, rtol=5e-4)


def test_remat_policy_validates_before_compile(session):
    """Unknown remat modes and roles fail eagerly with the offending
    token named — not as a shape error three layers into tracing."""
    ds = _linear_ds(session, n=64, parts=2)
    mesh = make_mesh(dict(stage=2, data=4))
    with pytest.raises(ValueError, match="unknown remat mode 'huge'"):
        _est(mesh=mesh, remat="kernel=huge").fit(ds)
    with pytest.raises(ValueError, match="unknown remat role 'attention'"):
        _est(mesh=mesh, remat="attention=dots").fit(ds)


def test_misplacement_fails_loud(session):
    """Placement misconfigurations raise actionable errors before any
    compile: layers must divide over stages, a staged mesh needs the
    layer-list model description, and the microbatch count must divide
    the batch."""
    from raydp_tpu.models import MLP

    ds = _linear_ds(session, n=64, parts=2)
    mesh = make_mesh(dict(stage=2, data=4))
    with pytest.raises(ValueError, match="stage=2 must divide"):
        _est(model=_model(3), mesh=mesh).fit(ds)
    with pytest.raises(ValueError, match="not a PipelineModel"):
        _est(model=MLP(features=(8,), use_batch_norm=False),
             mesh=mesh).fit(ds)
    with pytest.raises(ValueError, match="accum_steps=5"):
        _est(mesh=mesh, accum_steps=5).fit(ds)


def test_pipeline_model_description_contract():
    """PipelineModel is a description, not a module: empty layer lists and
    mutable collections (batch_stats) are rejected at init."""
    import jax

    with pytest.raises(ValueError, match="at least one layer"):
        PipelineModel(layers=[])

    class Stateful(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.BatchNorm(use_running_average=False)(x)

    with pytest.raises(ValueError, match="mutable"):
        PipelineModel(layers=[Stateful(), Stateful()]).init(
            jax.random.PRNGKey(0), np.zeros((4, DIM), np.float32))


def test_pipeline_chaos_epoch_crash_resumes_identically(session, tmp_path):
    """Chaos leg: an injected crash at ``estimator.epoch`` mid-fit on the
    staged mesh restores the epoch-0 checkpoint (stage-stacked params save
    and restore under their placed shardings) and replays to weights
    bit-identical to an uninterrupted staged fit."""
    ds = _linear_ds(session)

    def make(ckpt):
        return _est(mesh=make_mesh(dict(stage=2, data=4)), accum_steps=4,
                    checkpoint_dir=str(tmp_path / ckpt))

    clean = make("clean").fit(ds)
    assert len(clean.history) == 3

    faults.clear()
    try:
        rule = faults.inject("estimator.epoch", "raise", match="1", times=1)
        faulted = make("faulted").fit(ds, max_retries=1)
    finally:
        faults.clear()
    assert rule.fires == 1, "epoch fault never fired"
    assert len(faulted.history) == 3
    np.testing.assert_allclose(_losses(faulted), _losses(clean), rtol=5e-4)

    import jax

    a = jax.tree_util.tree_leaves(clean.state.params)
    b = jax.tree_util.tree_leaves(faulted.state.params)
    assert len(a) == len(b) and len(a) > 0
    for la, lb in zip(a, b):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))
