"""rdtlint: the tier-1 zero-violation fence over the real tree, plus
fixture-based units proving each rule fires on the bad shape and stays quiet
on the fixed one — including reproductions of the two historical deadlocks
(PR 3's read-loop-blocking late-result callback, PR 7's streaming
self-deadlock) and the two acceptance regressions (removing the
``DeferredReply`` hand-off from a streaming ``run_task``; removing the
``_patch_lock`` guard from an ``_ActionTemps``-shaped class)."""

import os
import textwrap

import pytest

from raydp_tpu.tools import rdtlint
from raydp_tpu.tools.rdtlint import run
from raydp_tpu.tools.rdtlint.__main__ import main as rdtlint_main

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "raydp_tpu")


# ---------------------------------------------------------------------------
# the fence: the whole package must be clean (suppressed-only)
# ---------------------------------------------------------------------------

def test_tree_is_clean():
    report = run([PKG], root=REPO)
    assert not report.unsuppressed, "\n" + report.render()
    # the suppression inventory is part of the reviewed surface: additions
    # must come through this file so the reason gets a second pair of eyes
    assert len(report.suppressed) <= 12, "\n" + report.render(True)


def test_cli_exit_codes(tmp_path, capsys):
    assert rdtlint_main([PKG, "--root", REPO]) == 0
    bad = _repo(tmp_path, {"pkg/m.py": "import os\n"
                           "V = os.environ.get('RDT_X')\n"})
    assert rdtlint_main([str(bad / "pkg"), "--root", str(bad)]) == 1
    # the fence must fail LOUDLY on a misconfigured path — a typo'd CI leg
    # reporting a clean tree would green-light anything forever
    assert rdtlint_main([str(tmp_path / "nonexistent")]) == 2
    (tmp_path / "empty").mkdir()
    assert rdtlint_main([str(tmp_path / "empty")]) == 2


# ---------------------------------------------------------------------------
# fixture plumbing
# ---------------------------------------------------------------------------

def _repo(tmp_path, files):
    """A throwaway repo: pyproject.toml marks the root; ``files`` maps
    relative paths to (dedented) contents."""
    (tmp_path / "pyproject.toml").write_text("[project]\nname='x'\n")
    for rel, content in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(content))
    return tmp_path


def _lint(tmp_path, files, rules=None):
    root = _repo(tmp_path, files)
    return run([str(root / "pkg")], root=str(root), rules=rules)


def _msgs(report, rule=None):
    return [v.message for v in report.unsuppressed
            if rule is None or v.rule == rule]


# ---------------------------------------------------------------------------
# rule 1: dispatcher-blocking
# ---------------------------------------------------------------------------

# the PR 7 shape: a streaming run_task that waits for seal notifications.
# GOOD = the shipped design (dedicated thread + DeferredReply); BAD = the
# acceptance regression (hand-off removed, the dispatcher thread waits)
_STREAM_COMMON = """
    import threading
    from concurrent.futures import Future


    class DeferredReply:
        def __init__(self, future):
            self.future = future


    class MethodDispatcher:
        def __init__(self, target):
            self._t = target


    class StreamExecutor:
        def __init__(self):
            self._sealed = threading.Event()

        def _stream_wait(self, task):
            # the consumed-stream wait: blocks until every map seals — maps
            # that may be queued BEHIND this very dispatcher thread
            self._sealed.wait()
            return task

        def _run_obj(self, task):
            return {"rows": 1}
"""

_STREAM_BAD = _STREAM_COMMON + """
        def run_task(self, task):
            if getattr(task, "streaming", False):
                return self._stream_wait(task)  # parks the dispatcher
            return self._run_obj(task)


    _server = MethodDispatcher(StreamExecutor())
"""

_STREAM_GOOD = _STREAM_COMMON + """
        def run_task(self, task):
            if getattr(task, "streaming", False):
                fut = Future()

                def _run():
                    fut.set_result(self._stream_wait(task))

                threading.Thread(target=_run, daemon=True).start()
                return DeferredReply(fut)
            return self._run_obj(task)


    _server = MethodDispatcher(StreamExecutor())
"""


def test_dispatcher_rule_catches_streaming_self_deadlock(tmp_path):
    report = _lint(tmp_path, {"pkg/ex.py": _STREAM_BAD},
                   rules=["dispatcher-blocking"])
    msgs = _msgs(report, "dispatcher-blocking")
    assert len(msgs) == 1 and "wait" in msgs[0] \
        and "run_task -> _stream_wait" in msgs[0]


def test_dispatcher_rule_accepts_deferred_reply_handoff(tmp_path):
    report = _lint(tmp_path, {"pkg/ex.py": _STREAM_GOOD},
                   rules=["dispatcher-blocking"])
    assert _msgs(report, "dispatcher-blocking") == []


# the PR 3 shape: a Future done-callback fires on the RPC connection's READ
# LOOP and synchronously calls back over that same connection
_CALLBACK_COMMON = """
    import threading


    class Pool:
        def __init__(self, client):
            self.client = client

        def _free_sync(self, fut):
            self.client.call("drop_blocks", fut)

        def watch(self, fut):
            fut.add_done_callback(self._free_late)
"""

_CALLBACK_BAD = _CALLBACK_COMMON + """
        def _free_late(self, fut):
            # blocks the only thread able to deliver its own response
            self._free_sync(fut)
"""

_CALLBACK_GOOD = _CALLBACK_COMMON + """
        def _free_late(self, fut):
            threading.Thread(target=self._free_sync, args=(fut,),
                             daemon=True).start()
"""


def test_dispatcher_rule_catches_read_loop_blocking_callback(tmp_path):
    report = _lint(tmp_path, {"pkg/pool.py": _CALLBACK_BAD},
                   rules=["dispatcher-blocking"])
    msgs = _msgs(report, "dispatcher-blocking")
    assert len(msgs) == 1 and "RpcClient.call" in msgs[0] \
        and "completion callback" in msgs[0]


def test_dispatcher_rule_accepts_thread_handoff_callback(tmp_path):
    report = _lint(tmp_path, {"pkg/pool.py": _CALLBACK_GOOD},
                   rules=["dispatcher-blocking"])
    assert _msgs(report, "dispatcher-blocking") == []


def test_dispatcher_rule_heuristics(tmp_path):
    # str.join / os.path.join / dict.get never count as blocking; sleep,
    # thread join, and store get do — and a reasoned allow suppresses
    src = """
    import os
    import time


    class MethodDispatcher:
        def __init__(self, t):
            pass


    class Svc:
        def fine(self, parts, d):
            x = ", ".join(parts)
            y = os.path.join("a", "b")
            return d.get("k"), x, y

        def slow(self):
            time.sleep(1.0)  # rdtlint: allow[dispatcher-blocking] test stub

        def joins(self, t):
            t.join()

        def reads(self, client):
            return client.get("oid")


    _s = MethodDispatcher(Svc())
    """
    report = _lint(tmp_path, {"pkg/svc.py": src},
                   rules=["dispatcher-blocking"])
    msgs = _msgs(report, "dispatcher-blocking")
    assert len(msgs) == 2
    assert any("thread join" in m for m in msgs)
    assert any("store/queue get" in m for m in msgs)
    assert len(report.suppressed) == 1  # the reasoned sleep


def test_dispatcher_rule_follows_annotated_attribute(tmp_path):
    # the self._job._wait(...) shape: resolution through an __init__
    # parameter annotation (how the SPMD coordinator deadlock was found)
    src = """
    class Job:
        def wait_thing(self, t):
            self._cond.wait(t)


    class Service:
        def __init__(self, job: "Job"):
            self._job = job

        def get_thing(self, t):
            return self._job.wait_thing(t)


    class MethodDispatcher:
        def __init__(self, t):
            pass


    _s = MethodDispatcher(Service(None))
    """
    report = _lint(tmp_path, {"pkg/svc.py": src},
                   rules=["dispatcher-blocking"])
    msgs = _msgs(report, "dispatcher-blocking")
    assert len(msgs) == 1 and "get_thing -> wait_thing" in msgs[0]


# ---------------------------------------------------------------------------
# rule 2: lock-discipline
# ---------------------------------------------------------------------------

# the _ActionTemps shape: ref_patches guarded by _patch_lock. BAD = the
# acceptance regression (lock removed from apply_patches)
_TEMPS = """
    import threading


    class Temps:
        def __init__(self):
            self.ref_patches = {}  # guarded-by: _patch_lock
            self._patch_lock = threading.Lock()

        def apply_patches(self, mapping):
            {body}
"""

_TEMPS_GOOD_BODY = """\
            with self._patch_lock:
                for k, v in mapping.items():
                    self.ref_patches[k] = v
"""

_TEMPS_BAD_BODY = """\
            for k, v in mapping.items():
                self.ref_patches[k] = v
"""


def test_lock_rule_catches_unguarded_patch_map(tmp_path):
    src = _TEMPS.replace("            {body}", _TEMPS_BAD_BODY)
    report = _lint(tmp_path, {"pkg/temps.py": src},
                   rules=["lock-discipline"])
    msgs = _msgs(report, "lock-discipline")
    assert msgs and "ref_patches" in msgs[0] and "_patch_lock" in msgs[0]


def test_lock_rule_accepts_guarded_patch_map(tmp_path):
    src = _TEMPS.replace("            {body}", _TEMPS_GOOD_BODY)
    report = _lint(tmp_path, {"pkg/temps.py": src},
                   rules=["lock-discipline"])
    assert _msgs(report, "lock-discipline") == []


def test_lock_rule_method_level_annotation_and_init_exemption(tmp_path):
    src = """
    import threading


    class Ledger:
        def __init__(self):
            self._lock = threading.Lock()
            self._stages = {}  # guarded-by: _lock
            self._stages["boot"] = 1  # __init__ is exempt

        def _resp_locked(self, key):  # guarded-by: _lock
            return self._stages.get(key)

        def publish(self, key):
            with self._lock:
                self._stages[key] = 1
                return self._resp_locked(key)

        def peek(self, key):
            # rdtlint: allow[lock-discipline] racy read tolerated in test
            return self._stages.get(key)

        def broken(self, key):
            return self._stages.get(key)
    """
    report = _lint(tmp_path, {"pkg/ledger.py": src},
                   rules=["lock-discipline"])
    msgs = _msgs(report, "lock-discipline")
    assert len(msgs) == 1 and "broken()" in msgs[0]
    assert len(report.suppressed) == 1


def test_lock_rule_registers_annotation_on_continuation_line(tmp_path):
    # the _StreamStageRec.seals shape: a wrapped initializer carrying the
    # guard comment on its continuation line must still register
    src = """
    import threading


    class Rec:
        def __init__(self, n):
            self._lock = threading.Lock()
            self.seals = \\
                [None] * n  # guarded-by: _lock

        def bad(self, i):
            return self.seals[i]

        def good(self, i):
            with self._lock:
                return self.seals[i]
    """
    report = _lint(tmp_path, {"pkg/rec.py": src}, rules=["lock-discipline"])
    msgs = _msgs(report, "lock-discipline")
    assert len(msgs) == 1 and "bad()" in msgs[0] and "seals" in msgs[0]


def test_lock_rule_trailing_comment_does_not_leak_to_next_line(tmp_path):
    src = """
    import threading


    class C:
        def __init__(self):
            self._lock = threading.Lock()
            self._guarded = {}  # guarded-by: _lock
            self._free = 0

        def touch(self):
            self._free += 1  # NOT guarded: must not inherit the annotation
    """
    report = _lint(tmp_path, {"pkg/c.py": src}, rules=["lock-discipline"])
    assert _msgs(report, "lock-discipline") == []


# ---------------------------------------------------------------------------
# rule 3: knob-registry
# ---------------------------------------------------------------------------

_FIXTURE_KNOBS = """
    from dataclasses import dataclass


    @dataclass
    class Knob:
        name: str
        scope: str


    KNOBS = {
        "RDT_GOOD": Knob("RDT_GOOD", "per-action"),
        "RDT_BOOT": Knob("RDT_BOOT", "process-start"),
    }
    DOC_TABLES = ()


    def table_markers(category):
        return ("<!-- b -->", "<!-- e -->")


    def render_block(category):
        return ""


    def get(name):
        return None
"""


def test_knob_rule_flags_direct_reads_and_resolves_constants(tmp_path):
    src = """
    import os

    ENV_NAME = "RDT_VIA_CONSTANT"


    def read():
        a = os.environ.get("RDT_DIRECT")
        b = os.environ[ENV_NAME]
        c = os.getenv("RDT_THIRD", "1")
        os.environ["RDT_WRITE"] = "1"  # writes are fine
        return a, b, c
    """
    report = _lint(tmp_path, {"pkg/m.py": src}, rules=["knob-registry"])
    msgs = _msgs(report, "knob-registry")
    assert len(msgs) == 3
    assert any("RDT_VIA_CONSTANT" in m for m in msgs)
    assert not any("RDT_WRITE" in m for m in msgs)


def test_knob_rule_registry_membership_and_import_time_cache(tmp_path):
    src = """
    from pkg import knobs

    CACHED = knobs.get("RDT_GOOD")           # per-action at import: flagged
    BOOT = knobs.get("RDT_BOOT")             # process-start at import: fine


    def f(x=knobs.get("RDT_GOOD")):          # defaults run at def time
        return x


    def g():
        ok = knobs.get("RDT_GOOD")           # call-time read: fine
        return ok, knobs.get("RDT_MISSING")  # unregistered: flagged
    """
    report = _lint(tmp_path, {"pkg/knobs.py": _FIXTURE_KNOBS,
                              "pkg/m.py": src}, rules=["knob-registry"])
    msgs = _msgs(report, "knob-registry")
    import_time = [m for m in msgs if "import time" in m]
    assert len(import_time) == 2
    assert any("RDT_MISSING" in m and "not declared" in m for m in msgs)
    assert not any("RDT_BOOT" in m and "import time" in m for m in msgs)


def test_knob_rule_flags_dead_registry_entries(tmp_path):
    report = _lint(tmp_path, {
        "pkg/knobs.py": _FIXTURE_KNOBS,
        "pkg/m.py": "from pkg import knobs\n\n\n"
                    "def f():\n    return knobs.get('RDT_GOOD')\n"},
        rules=["knob-registry"])
    msgs = _msgs(report, "knob-registry")
    assert any("RDT_BOOT" in m and "no linted code references" in m
               for m in msgs)


def test_real_registry_docs_and_defaults():
    from raydp_tpu import knobs

    # the generated tables cover every knob, and get() honors defaults,
    # parsing, and the empty-string-is-unset contract
    table = knobs.generate_table()
    for name in knobs.KNOBS:
        assert f"`{name}`" in table
    assert knobs.get("RDT_LINEAGE_ROUNDS") == 4
    old = os.environ.pop("RDT_LINEAGE_ROUNDS", None)
    try:
        os.environ["RDT_LINEAGE_ROUNDS"] = ""
        assert knobs.get("RDT_LINEAGE_ROUNDS") == 4
        os.environ["RDT_LINEAGE_ROUNDS"] = "2.0"
        assert knobs.get("RDT_LINEAGE_ROUNDS") == 2
        os.environ["RDT_ETL_AQE"] = "off"
        assert knobs.get("RDT_ETL_AQE") is False
    finally:
        os.environ.pop("RDT_ETL_AQE", None)
        if old is None:
            os.environ.pop("RDT_LINEAGE_ROUNDS", None)
        else:
            os.environ["RDT_LINEAGE_ROUNDS"] = old
    with pytest.raises(KeyError):
        knobs.get("RDT_NOT_A_KNOB")
    with pytest.raises(KeyError):
        knobs.require("RDT_SPMD_JOB_ID")


# ---------------------------------------------------------------------------
# rule 4: fault-site-sync
# ---------------------------------------------------------------------------

_FIXTURE_FAULTS = """
    KNOWN_SITES = frozenset((
        "good.site",
        "stale.site",
    ))


    def check(site, key=""):
        return None
"""


def test_fault_rule_cross_checks_code_registry_tests_and_docs(tmp_path):
    root = _repo(tmp_path, {
        "pkg/faults.py": _FIXTURE_FAULTS,
        "pkg/m.py": """
            from pkg import faults


            def f():
                faults.check("good.site", key="k")
                faults.check("rogue.site", key="k")
            """,
        "tests/test_x.py": """
            SPEC = "good.site:drop:nth=1"
            GHOST = "ghost.site:crash:once=/tmp/s"
            """,
        "doc/fault_tolerance.md": """
            | Site | Fires at | Actions |
            | --- | --- | --- |
            | `good.site` | somewhere | `drop` |
            | `phantom.site` | nowhere | `crash` |
            """,
    })
    report = run([str(root / "pkg")], root=str(root),
                 rules=["fault-site-sync"])
    msgs = _msgs(report, "fault-site-sync")
    assert any("'rogue.site'" in m and "KNOWN_SITES" in m for m in msgs)
    assert any("'stale.site'" in m and "stale registry" in m for m in msgs)
    assert any("'ghost.site'" in m and "inject nothing" in m for m in msgs)
    assert any("'phantom.site'" in m for m in msgs)
    # the documented + armed + registered site is never flagged
    assert not any("'good.site'" in m for m in msgs)


def test_fault_rule_quiet_on_consistent_fixture(tmp_path):
    root = _repo(tmp_path, {
        "pkg/faults.py": """
            KNOWN_SITES = frozenset(("only.site",))


            def check(site, key=""):
                return None
            """,
        "pkg/m.py": """
            from pkg import faults


            def f():
                faults.check("only.site")
            """,
        "tests/test_x.py": 'S = "only.site:delay:ms=5"\n',
        "doc/fault_tolerance.md":
            "| Site | Fires at | Actions |\n| --- | --- | --- |\n"
            "| `only.site` | f | `delay` |\n",
    })
    report = run([str(root / "pkg")], root=str(root),
                 rules=["fault-site-sync"])
    assert _msgs(report, "fault-site-sync") == []


def test_real_parse_spec_sites_match_lint_registry():
    # the lint's view of KNOWN_SITES and the runtime's must be the same
    # object: a drifted copy would let the fence and the parser disagree
    from raydp_tpu import faults
    from raydp_tpu.tools.rdtlint.core import Project
    from raydp_tpu.tools.rdtlint.rule_faults import _code_sites, _known_sites

    project = Project.load([PKG], root=REPO)
    declared, _line = _known_sites(project.find_file("faults.py"))
    assert declared == set(faults.KNOWN_SITES)
    assert set(_code_sites(project)) == set(faults.KNOWN_SITES)


# ---------------------------------------------------------------------------
# suppression mechanics
# ---------------------------------------------------------------------------

def test_suppression_requires_reason(tmp_path):
    src = """
    import os

    A = os.environ.get("RDT_A")  # rdtlint: allow[knob-registry]
    # rdtlint: allow[knob-registry] reasoned: fixture exercising suppression
    B = os.environ.get("RDT_B")
    """
    report = _lint(tmp_path, {"pkg/m.py": src}, rules=["knob-registry"])
    msgs = _msgs(report, "knob-registry")
    assert len(msgs) == 1 and "RDT_A" in msgs[0]
    assert len(report.suppressed) == 1
