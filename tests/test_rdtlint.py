"""rdtlint: the tier-1 zero-violation fence over the real tree, plus
fixture-based units proving each rule fires on the bad shape and stays quiet
on the fixed one — including reproductions of the two historical deadlocks
(PR 3's read-loop-blocking late-result callback, PR 7's streaming
self-deadlock), the two acceptance regressions (removing the
``DeferredReply`` hand-off from a streaming ``run_task``; removing the
``_patch_lock`` guard from an ``_ActionTemps``-shaped class), and — for the
cross-process contract families — real-tree mutation fences: deleting a
``patch_task_refs`` branch, a head ``store_*`` proxy, or a
``_result_refs`` key, and renaming a contract exception, must each break
the fence."""

import json
import os
import shutil
import textwrap

import pytest

from raydp_tpu.tools import rdtlint
from raydp_tpu.tools.rdtlint import run
from raydp_tpu.tools.rdtlint.__main__ import main as rdtlint_main

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "raydp_tpu")


# ---------------------------------------------------------------------------
# the fence: the whole package must be clean (suppressed-only)
# ---------------------------------------------------------------------------

def test_tree_is_clean():
    report = run([PKG], root=REPO)
    assert not report.unsuppressed, "\n" + report.render()
    # the suppression inventory is part of the reviewed surface: additions
    # must come through this file so the reason gets a second pair of eyes
    assert len(report.suppressed) <= 12, "\n" + report.render(True)


def test_tests_and_benchmarks_knob_fault_scan_is_clean():
    """The CI sweep leg: the knob, fault-site, and telemetry families over
    tests/ and benchmarks/ too — direct RDT_* env reads (and unregistered
    span/metric literals) in test code used to escape the package leg
    entirely."""
    report = run([PKG, os.path.join(REPO, "tests"),
                  os.path.join(REPO, "benchmarks")], root=REPO,
                 rules=["knob-registry", "fault-site-sync",
                        "telemetry-registry"])
    assert not report.unsuppressed, "\n" + report.render()


def test_cli_exit_codes(tmp_path, capsys):
    assert rdtlint_main([PKG, "--root", REPO]) == 0
    bad = _repo(tmp_path, {"pkg/m.py": "import os\n"
                           "V = os.environ.get('RDT_X')\n"})
    assert rdtlint_main([str(bad / "pkg"), "--root", str(bad)]) == 1
    # the fence must fail LOUDLY on a misconfigured path — a typo'd CI leg
    # reporting a clean tree would green-light anything forever
    assert rdtlint_main([str(tmp_path / "nonexistent")]) == 2
    (tmp_path / "empty").mkdir()
    assert rdtlint_main([str(tmp_path / "empty")]) == 2


# ---------------------------------------------------------------------------
# fixture plumbing
# ---------------------------------------------------------------------------

def _repo(tmp_path, files):
    """A throwaway repo: pyproject.toml marks the root; ``files`` maps
    relative paths to (dedented) contents."""
    (tmp_path / "pyproject.toml").write_text("[project]\nname='x'\n")
    for rel, content in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(content))
    return tmp_path


def _lint(tmp_path, files, rules=None):
    root = _repo(tmp_path, files)
    return run([str(root / "pkg")], root=str(root), rules=rules)


def _msgs(report, rule=None):
    return [v.message for v in report.unsuppressed
            if rule is None or v.rule == rule]


# ---------------------------------------------------------------------------
# rule 1: dispatcher-blocking
# ---------------------------------------------------------------------------

# the PR 7 shape: a streaming run_task that waits for seal notifications.
# GOOD = the shipped design (dedicated thread + DeferredReply); BAD = the
# acceptance regression (hand-off removed, the dispatcher thread waits)
_STREAM_COMMON = """
    import threading
    from concurrent.futures import Future


    class DeferredReply:
        def __init__(self, future):
            self.future = future


    class MethodDispatcher:
        def __init__(self, target):
            self._t = target


    class StreamExecutor:
        def __init__(self):
            self._sealed = threading.Event()

        def _stream_wait(self, task):
            # the consumed-stream wait: blocks until every map seals — maps
            # that may be queued BEHIND this very dispatcher thread
            self._sealed.wait()
            return task

        def _run_obj(self, task):
            return {"rows": 1}
"""

_STREAM_BAD = _STREAM_COMMON + """
        def run_task(self, task):
            if getattr(task, "streaming", False):
                return self._stream_wait(task)  # parks the dispatcher
            return self._run_obj(task)


    _server = MethodDispatcher(StreamExecutor())
"""

_STREAM_GOOD = _STREAM_COMMON + """
        def run_task(self, task):
            if getattr(task, "streaming", False):
                fut = Future()

                def _run():
                    fut.set_result(self._stream_wait(task))

                threading.Thread(target=_run, daemon=True).start()
                return DeferredReply(fut)
            return self._run_obj(task)


    _server = MethodDispatcher(StreamExecutor())
"""


def test_dispatcher_rule_catches_streaming_self_deadlock(tmp_path):
    report = _lint(tmp_path, {"pkg/ex.py": _STREAM_BAD},
                   rules=["dispatcher-blocking"])
    msgs = _msgs(report, "dispatcher-blocking")
    assert len(msgs) == 1 and "wait" in msgs[0] \
        and "run_task -> _stream_wait" in msgs[0]


def test_dispatcher_rule_accepts_deferred_reply_handoff(tmp_path):
    report = _lint(tmp_path, {"pkg/ex.py": _STREAM_GOOD},
                   rules=["dispatcher-blocking"])
    assert _msgs(report, "dispatcher-blocking") == []


# the PR 3 shape: a Future done-callback fires on the RPC connection's READ
# LOOP and synchronously calls back over that same connection
_CALLBACK_COMMON = """
    import threading


    class Pool:
        def __init__(self, client):
            self.client = client

        def _free_sync(self, fut):
            self.client.call("drop_blocks", fut)

        def watch(self, fut):
            fut.add_done_callback(self._free_late)
"""

_CALLBACK_BAD = _CALLBACK_COMMON + """
        def _free_late(self, fut):
            # blocks the only thread able to deliver its own response
            self._free_sync(fut)
"""

_CALLBACK_GOOD = _CALLBACK_COMMON + """
        def _free_late(self, fut):
            threading.Thread(target=self._free_sync, args=(fut,),
                             daemon=True).start()
"""


def test_dispatcher_rule_catches_read_loop_blocking_callback(tmp_path):
    report = _lint(tmp_path, {"pkg/pool.py": _CALLBACK_BAD},
                   rules=["dispatcher-blocking"])
    msgs = _msgs(report, "dispatcher-blocking")
    assert len(msgs) == 1 and "RpcClient.call" in msgs[0] \
        and "completion callback" in msgs[0]


def test_dispatcher_rule_accepts_thread_handoff_callback(tmp_path):
    report = _lint(tmp_path, {"pkg/pool.py": _CALLBACK_GOOD},
                   rules=["dispatcher-blocking"])
    assert _msgs(report, "dispatcher-blocking") == []


def test_dispatcher_rule_heuristics(tmp_path):
    # str.join / os.path.join / dict.get never count as blocking; sleep,
    # thread join, and store get do — and a reasoned allow suppresses
    src = """
    import os
    import time


    class MethodDispatcher:
        def __init__(self, t):
            pass


    class Svc:
        def fine(self, parts, d):
            x = ", ".join(parts)
            y = os.path.join("a", "b")
            return d.get("k"), x, y

        def slow(self):
            time.sleep(1.0)  # rdtlint: allow[dispatcher-blocking] test stub

        def joins(self, t):
            t.join()

        def reads(self, client):
            return client.get("oid")


    _s = MethodDispatcher(Svc())
    """
    report = _lint(tmp_path, {"pkg/svc.py": src},
                   rules=["dispatcher-blocking"])
    msgs = _msgs(report, "dispatcher-blocking")
    assert len(msgs) == 2
    assert any("thread join" in m for m in msgs)
    assert any("store/queue get" in m for m in msgs)
    assert len(report.suppressed) == 1  # the reasoned sleep


def test_dispatcher_rule_follows_annotated_attribute(tmp_path):
    # the self._job._wait(...) shape: resolution through an __init__
    # parameter annotation (how the SPMD coordinator deadlock was found)
    src = """
    class Job:
        def wait_thing(self, t):
            self._cond.wait(t)


    class Service:
        def __init__(self, job: "Job"):
            self._job = job

        def get_thing(self, t):
            return self._job.wait_thing(t)


    class MethodDispatcher:
        def __init__(self, t):
            pass


    _s = MethodDispatcher(Service(None))
    """
    report = _lint(tmp_path, {"pkg/svc.py": src},
                   rules=["dispatcher-blocking"])
    msgs = _msgs(report, "dispatcher-blocking")
    assert len(msgs) == 1 and "get_thing -> wait_thing" in msgs[0]


# ---------------------------------------------------------------------------
# rule 2: lock-discipline
# ---------------------------------------------------------------------------

# the _ActionTemps shape: ref_patches guarded by _patch_lock. BAD = the
# acceptance regression (lock removed from apply_patches)
_TEMPS = """
    import threading


    class Temps:
        def __init__(self):
            self.ref_patches = {}  # guarded-by: _patch_lock
            self._patch_lock = threading.Lock()

        def apply_patches(self, mapping):
            {body}
"""

_TEMPS_GOOD_BODY = """\
            with self._patch_lock:
                for k, v in mapping.items():
                    self.ref_patches[k] = v
"""

_TEMPS_BAD_BODY = """\
            for k, v in mapping.items():
                self.ref_patches[k] = v
"""


def test_lock_rule_catches_unguarded_patch_map(tmp_path):
    src = _TEMPS.replace("            {body}", _TEMPS_BAD_BODY)
    report = _lint(tmp_path, {"pkg/temps.py": src},
                   rules=["lock-discipline"])
    msgs = _msgs(report, "lock-discipline")
    assert msgs and "ref_patches" in msgs[0] and "_patch_lock" in msgs[0]


def test_lock_rule_accepts_guarded_patch_map(tmp_path):
    src = _TEMPS.replace("            {body}", _TEMPS_GOOD_BODY)
    report = _lint(tmp_path, {"pkg/temps.py": src},
                   rules=["lock-discipline"])
    assert _msgs(report, "lock-discipline") == []


def test_lock_rule_method_level_annotation_and_init_exemption(tmp_path):
    src = """
    import threading


    class Ledger:
        def __init__(self):
            self._lock = threading.Lock()
            self._stages = {}  # guarded-by: _lock
            self._stages["boot"] = 1  # __init__ is exempt

        def _resp_locked(self, key):  # guarded-by: _lock
            return self._stages.get(key)

        def publish(self, key):
            with self._lock:
                self._stages[key] = 1
                return self._resp_locked(key)

        def peek(self, key):
            # rdtlint: allow[lock-discipline] racy read tolerated in test
            return self._stages.get(key)

        def broken(self, key):
            return self._stages.get(key)
    """
    report = _lint(tmp_path, {"pkg/ledger.py": src},
                   rules=["lock-discipline"])
    msgs = _msgs(report, "lock-discipline")
    assert len(msgs) == 1 and "broken()" in msgs[0]
    assert len(report.suppressed) == 1


def test_lock_rule_registers_annotation_on_continuation_line(tmp_path):
    # the _StreamStageRec.seals shape: a wrapped initializer carrying the
    # guard comment on its continuation line must still register
    src = """
    import threading


    class Rec:
        def __init__(self, n):
            self._lock = threading.Lock()
            self.seals = \\
                [None] * n  # guarded-by: _lock

        def bad(self, i):
            return self.seals[i]

        def good(self, i):
            with self._lock:
                return self.seals[i]
    """
    report = _lint(tmp_path, {"pkg/rec.py": src}, rules=["lock-discipline"])
    msgs = _msgs(report, "lock-discipline")
    assert len(msgs) == 1 and "bad()" in msgs[0] and "seals" in msgs[0]


def test_lock_rule_trailing_comment_does_not_leak_to_next_line(tmp_path):
    src = """
    import threading


    class C:
        def __init__(self):
            self._lock = threading.Lock()
            self._guarded = {}  # guarded-by: _lock
            self._free = 0

        def touch(self):
            self._free += 1  # NOT guarded: must not inherit the annotation
    """
    report = _lint(tmp_path, {"pkg/c.py": src}, rules=["lock-discipline"])
    assert _msgs(report, "lock-discipline") == []


# ---------------------------------------------------------------------------
# rule 3: knob-registry
# ---------------------------------------------------------------------------

_FIXTURE_KNOBS = """
    from dataclasses import dataclass


    @dataclass
    class Knob:
        name: str
        scope: str


    KNOBS = {
        "RDT_GOOD": Knob("RDT_GOOD", "per-action"),
        "RDT_BOOT": Knob("RDT_BOOT", "process-start"),
    }
    DOC_TABLES = ()


    def table_markers(category):
        return ("<!-- b -->", "<!-- e -->")


    def render_block(category):
        return ""


    def get(name):
        return None
"""


def test_knob_rule_flags_direct_reads_and_resolves_constants(tmp_path):
    src = """
    import os

    ENV_NAME = "RDT_VIA_CONSTANT"


    def read():
        a = os.environ.get("RDT_DIRECT")
        b = os.environ[ENV_NAME]
        c = os.getenv("RDT_THIRD", "1")
        os.environ["RDT_WRITE"] = "1"  # writes are fine
        return a, b, c
    """
    report = _lint(tmp_path, {"pkg/m.py": src}, rules=["knob-registry"])
    msgs = _msgs(report, "knob-registry")
    assert len(msgs) == 3
    assert any("RDT_VIA_CONSTANT" in m for m in msgs)
    assert not any("RDT_WRITE" in m for m in msgs)


def test_knob_rule_registry_membership_and_import_time_cache(tmp_path):
    src = """
    from pkg import knobs

    CACHED = knobs.get("RDT_GOOD")           # per-action at import: flagged
    BOOT = knobs.get("RDT_BOOT")             # process-start at import: fine


    def f(x=knobs.get("RDT_GOOD")):          # defaults run at def time
        return x


    def g():
        ok = knobs.get("RDT_GOOD")           # call-time read: fine
        return ok, knobs.get("RDT_MISSING")  # unregistered: flagged
    """
    report = _lint(tmp_path, {"pkg/knobs.py": _FIXTURE_KNOBS,
                              "pkg/m.py": src}, rules=["knob-registry"])
    msgs = _msgs(report, "knob-registry")
    import_time = [m for m in msgs if "import time" in m]
    assert len(import_time) == 2
    assert any("RDT_MISSING" in m and "not declared" in m for m in msgs)
    assert not any("RDT_BOOT" in m and "import time" in m for m in msgs)


def test_knob_rule_flags_dead_registry_entries(tmp_path):
    report = _lint(tmp_path, {
        "pkg/knobs.py": _FIXTURE_KNOBS,
        "pkg/m.py": "from pkg import knobs\n\n\n"
                    "def f():\n    return knobs.get('RDT_GOOD')\n"},
        rules=["knob-registry"])
    msgs = _msgs(report, "knob-registry")
    assert any("RDT_BOOT" in m and "no linted code references" in m
               for m in msgs)


def test_real_registry_docs_and_defaults():
    from raydp_tpu import knobs

    # the generated tables cover every knob, and get() honors defaults,
    # parsing, and the empty-string-is-unset contract
    table = knobs.generate_table()
    for name in knobs.KNOBS:
        assert f"`{name}`" in table
    assert knobs.get("RDT_LINEAGE_ROUNDS") == 4
    old = os.environ.pop("RDT_LINEAGE_ROUNDS", None)
    try:
        os.environ["RDT_LINEAGE_ROUNDS"] = ""
        assert knobs.get("RDT_LINEAGE_ROUNDS") == 4
        os.environ["RDT_LINEAGE_ROUNDS"] = "2.0"
        assert knobs.get("RDT_LINEAGE_ROUNDS") == 2
        os.environ["RDT_ETL_AQE"] = "off"
        assert knobs.get("RDT_ETL_AQE") is False
    finally:
        os.environ.pop("RDT_ETL_AQE", None)
        if old is None:
            os.environ.pop("RDT_LINEAGE_ROUNDS", None)
        else:
            os.environ["RDT_LINEAGE_ROUNDS"] = old
    with pytest.raises(KeyError):
        # rdtlint: allow[knob-registry] deliberately unregistered: pins the KeyError
        knobs.get("RDT_NOT_A_KNOB")
    with pytest.raises(KeyError):
        knobs.require("RDT_SPMD_JOB_ID")


# ---------------------------------------------------------------------------
# rule 4: fault-site-sync
# ---------------------------------------------------------------------------

_FIXTURE_FAULTS = """
    KNOWN_SITES = frozenset((
        "good.site",
        "stale.site",
    ))


    def check(site, key=""):
        return None
"""


def test_fault_rule_cross_checks_code_registry_tests_and_docs(tmp_path):
    root = _repo(tmp_path, {
        "pkg/faults.py": _FIXTURE_FAULTS,
        "pkg/m.py": """
            from pkg import faults


            def f():
                faults.check("good.site", key="k")
                faults.check("rogue.site", key="k")
            """,
        "tests/test_x.py": """
            SPEC = "good.site:drop:nth=1"
            GHOST = "ghost.site:crash:once=/tmp/s"
            """,
        "doc/fault_tolerance.md": """
            | Site | Fires at | Actions |
            | --- | --- | --- |
            | `good.site` | somewhere | `drop` |
            | `phantom.site` | nowhere | `crash` |
            """,
    })
    report = run([str(root / "pkg")], root=str(root),
                 rules=["fault-site-sync"])
    msgs = _msgs(report, "fault-site-sync")
    assert any("'rogue.site'" in m and "KNOWN_SITES" in m for m in msgs)
    assert any("'stale.site'" in m and "stale registry" in m for m in msgs)
    assert any("'ghost.site'" in m and "inject nothing" in m for m in msgs)
    assert any("'phantom.site'" in m for m in msgs)
    # the documented + armed + registered site is never flagged
    assert not any("'good.site'" in m for m in msgs)


def test_fault_rule_quiet_on_consistent_fixture(tmp_path):
    root = _repo(tmp_path, {
        "pkg/faults.py": """
            KNOWN_SITES = frozenset(("only.site",))


            def check(site, key=""):
                return None
            """,
        "pkg/m.py": """
            from pkg import faults


            def f():
                faults.check("only.site")
            """,
        "tests/test_x.py": 'S = "only.site:delay:ms=5"\n',
        "doc/fault_tolerance.md":
            "| Site | Fires at | Actions |\n| --- | --- | --- |\n"
            "| `only.site` | f | `delay` |\n",
    })
    report = run([str(root / "pkg")], root=str(root),
                 rules=["fault-site-sync"])
    assert _msgs(report, "fault-site-sync") == []


def test_real_parse_spec_sites_match_lint_registry():
    # the lint's view of KNOWN_SITES and the runtime's must be the same
    # object: a drifted copy would let the fence and the parser disagree
    from raydp_tpu import faults
    from raydp_tpu.tools.rdtlint.core import Project
    from raydp_tpu.tools.rdtlint.rule_faults import _code_sites, _known_sites

    project = Project.load([PKG], root=REPO)
    declared, _line = _known_sites(project.find_file("faults.py"))
    assert declared == set(faults.KNOWN_SITES)
    assert set(_code_sites(project)) == set(faults.KNOWN_SITES)


# ---------------------------------------------------------------------------
# suppression mechanics
# ---------------------------------------------------------------------------

def test_suppression_requires_reason(tmp_path):
    src = """
    import os

    A = os.environ.get("RDT_A")  # rdtlint: allow[knob-registry]
    # rdtlint: allow[knob-registry] reasoned: fixture exercising suppression
    B = os.environ.get("RDT_B")
    """
    report = _lint(tmp_path, {"pkg/m.py": src}, rules=["knob-registry"])
    msgs = _msgs(report, "knob-registry")
    assert len(msgs) == 1 and "RDT_A" in msgs[0]
    assert len(report.suppressed) == 1


# ---------------------------------------------------------------------------
# rule 5: rpc-surface
# ---------------------------------------------------------------------------

# a config-known surface class (HeadService) so the mapped receiver "head"
# resolves strictly against it
_RPC_SERVER = """
    class MethodDispatcher:
        def __init__(self, t):
            self._t = t


    class HeadService:
        def lookup(self, object_id):
            return object_id

        def seal(self, object_id, segment, size, kind="raw"):
            return True

        def ping(self):
            return "pong"


    _dispatch = MethodDispatcher(HeadService())
"""

_RPC_BAD_CLIENT = """
    def drive(head):
        head.call("lokup", "oid")                       # typo'd name
        head.call("seal", "oid")                        # arity: needs 3
        head.call("seal", "oid", "seg", 1, junk=True)   # unknown keyword
        head.call("_reset")                             # underscore target
"""

_RPC_GOOD_CLIENT = """
    def drive(head, handle):
        head.call("lookup", "oid", timeout=5.0)      # timeout= is excluded
        head.call("seal", "oid", "seg", 3)           # kind= has a default
        head.call("seal", "oid", "seg", 3, kind="arrow")
        handle.call("__rdt_spans__", timeout=10.0)   # actor intrinsic
        head.call(method, "oid")                     # variable name: no check
"""


def test_rpc_rule_catches_typo_arity_and_underscore(tmp_path):
    report = _lint(tmp_path, {"pkg/head.py": _RPC_SERVER,
                              "pkg/client.py": _RPC_BAD_CLIENT},
                   rules=["rpc-surface"])
    msgs = _msgs(report, "rpc-surface")
    assert len(msgs) == 4
    assert any("'lokup'" in m and "resolves on no method" in m for m in msgs)
    assert any("requires 3" in m for m in msgs)
    assert any("unknown keyword 'junk'" in m for m in msgs)
    assert any("underscore method '_reset'" in m for m in msgs)


def test_rpc_rule_accepts_matching_calls(tmp_path):
    report = _lint(tmp_path, {"pkg/head.py": _RPC_SERVER,
                              "pkg/client.py": _RPC_GOOD_CLIENT},
                   rules=["rpc-surface"])
    assert _msgs(report, "rpc-surface") == []


_PROXY_STORE = """
    class ObjectStoreServer:
        def lookup(self, object_id):
            return object_id

        def seal(self, object_id, segment, size):
            return True

        def free(self, ids):
            return len(ids)


    class ObjectStoreClient:
        def __init__(self, server):
            self._server = server

        def get(self, oid):
            return self._server.lookup(oid)

        def put(self, oid):
            return self._server.seal(oid, "seg", 1)

        def free(self, ids):
            return self._server.free(ids)
"""

_PROXY_HEAD_GOOD = """
    class HeadService:
        def __init__(self, rt):
            self._rt = rt

        def store_lookup(self, *a):
            return self._rt.store_server.lookup(*a)

        def store_seal(self, *a):
            return self._rt.store_server.seal(*a)

        def store_free(self, *a):
            return self._rt.store_server.free(*a)
"""

# the drift shapes: the free proxy is gone, and store_lookup forwards to the
# WRONG server method (StoreTableProxy routes by name)
_PROXY_HEAD_BAD = """
    class HeadService:
        def __init__(self, rt):
            self._rt = rt

        def store_lookup(self, *a):
            return self._rt.store_server.seal(*a)

        def store_seal(self, *a):
            return self._rt.store_server.seal(*a)
"""


def test_rpc_rule_checks_head_proxy_completeness(tmp_path):
    report = _lint(tmp_path, {"pkg/object_store.py": _PROXY_STORE,
                              "pkg/head.py": _PROXY_HEAD_BAD},
                   rules=["rpc-surface"])
    msgs = _msgs(report, "rpc-surface")
    assert any("'free'" in m and "no store_free proxy" in m for m in msgs)
    assert any("store_lookup" in m and "wrong method" in m for m in msgs)


def test_rpc_rule_accepts_complete_proxy_surface(tmp_path):
    report = _lint(tmp_path, {"pkg/object_store.py": _PROXY_STORE,
                              "pkg/head.py": _PROXY_HEAD_GOOD},
                   rules=["rpc-surface"])
    assert _msgs(report, "rpc-surface") == []


_RPC_THREE_SURFACES = """
    class HeadService:
        def ping(self):
            return "pong"


    class NodeAgentService:
        def spawn(self, env, log_name):
            return 1


    class ObjectStoreServer:
        def lookup(self, object_id):
            return object_id
"""


def test_rpc_doc_table_drift_and_regeneration(tmp_path):
    root = _repo(tmp_path, {
        "pkg/services.py": _RPC_THREE_SURFACES,
        "doc/dev_lint.md": "# x\n\n<!-- rdtlint:rpc-table:begin -->\n"
                           "stale\n<!-- rdtlint:rpc-table:end -->\n",
    })
    report = run([str(root / "pkg")], root=str(root), rules=["rpc-surface"])
    assert any("stale" in m and "--write-rpc-docs" in m
               for m in _msgs(report, "rpc-surface"))
    assert rdtlint_main([str(root / "pkg"), "--root", str(root),
                         "--write-rpc-docs"]) == 0
    report = run([str(root / "pkg")], root=str(root), rules=["rpc-surface"])
    assert _msgs(report, "rpc-surface") == []
    text = (root / "doc" / "dev_lint.md").read_text()
    assert "`spawn`" in text and "`env, log_name`" in text


# ---------------------------------------------------------------------------
# rule 6: step-registry
# ---------------------------------------------------------------------------

_TASKS_FIXTURE = """
    from dataclasses import dataclass
    from typing import List


    class ObjectRef:
        id: str


    class Step:
        pass


    @dataclass
    class ArrowRefSource(Step):  {anno}
        refs: List[ObjectRef]


    @dataclass
    class PlainStep(Step):
        column: str


    def task_input_ids(task):
        if isinstance(task, ArrowRefSource):
            return [r.id for r in task.refs]
        return []


    def _patch_step_refs(step, mapping):
        {patch_body}
        return step


    def patch_task_refs(task, mapping):
        return _patch_step_refs(task, mapping)


    def stream_sources_of(task):
        return []


    def resolve_stream_sources(task, resolver):
        return task
"""

_PATCH_GOOD = """if isinstance(step, ArrowRefSource):
            step.refs = [mapping.get(r.id, r) for r in step.refs]"""
_PATCH_MISSING = "del mapping"


def _tasks_repo(tmp_path, anno="# carries-refs: refs",
                patch_body=_PATCH_GOOD):
    src = _TASKS_FIXTURE.replace("{anno}", anno) \
        .replace("        {patch_body}", "        " + patch_body)
    return _lint(tmp_path, {"pkg/etl/tasks.py": src},
                 rules=["step-registry"])


def test_step_rule_accepts_declared_and_handled_carrier(tmp_path):
    report = _tasks_repo(tmp_path)
    assert _msgs(report, "step-registry") == []


def test_step_rule_catches_undeclared_carrier(tmp_path):
    report = _tasks_repo(tmp_path, anno="")
    msgs = _msgs(report, "step-registry")
    assert len(msgs) == 1 and "ArrowRefSource" in msgs[0] \
        and "no `# carries-refs:` declaration" in msgs[0]


def test_step_rule_catches_unregistered_patch_handler(tmp_path):
    # the PR 6 BroadcastJoinStep regression shape: the class is declared but
    # its _patch_step_refs branch is gone
    report = _tasks_repo(tmp_path, patch_body=_PATCH_MISSING)
    msgs = _msgs(report, "step-registry")
    assert len(msgs) == 1 and "_patch_step_refs()" in msgs[0] \
        and "BroadcastJoinStep regression" in msgs[0]


def test_step_rule_catches_stale_declaration(tmp_path):
    report = _tasks_repo(tmp_path, anno="# carries-refs: refs, bogus")
    msgs = _msgs(report, "step-registry")
    assert len(msgs) == 1 and "'bogus'" in msgs[0] \
        and "stale declaration" in msgs[0]


# ---------------------------------------------------------------------------
# rule 7: exc-contract
# ---------------------------------------------------------------------------

_EXC_COMMON = {
    "pkg/rpc.py": """
        class RpcError(Exception):
            pass


        class ConnectionLost(RpcError):
            pass


        class RemoteError(RpcError):
            def __init__(self, exc_type):
                self.exc_type = exc_type
        """,
    "pkg/store.py": """
        class ObjectLostError(KeyError):
            pass
        """,
}

_EXC_GOOD = """
    _NO_RETRY = ("ValueError", "ObjectLostError")


    def handle(err):
        if err.exc_type == "ObjectLostError":
            return "recover"
        if err.exc_type in _NO_RETRY:
            return "fail"
        if getattr(err, "exc_type", None) == "FileNotFoundError":
            return "retry"
        if type(err).__name__ == "ConnectionLost":
            return "reconnect"
        return "other"
"""

_EXC_BAD = """
    _NO_RETRY = ("ValueError", "ShufleStreamAborted")


    def handle(err):
        if err.exc_type == "ObjectGoneError":
            return "recover"
        if err.exc_type in _NO_RETRY:
            return "fail"
        if type(err).__name__ == "ConectionLost":
            return "reconnect"
        return "other"
"""


def test_exc_rule_catches_stale_exception_strings(tmp_path):
    files = dict(_EXC_COMMON, **{"pkg/engine.py": _EXC_BAD})
    report = _lint(tmp_path, files, rules=["exc-contract"])
    msgs = _msgs(report, "exc-contract")
    assert len(msgs) == 3
    for name in ("ObjectGoneError", "ShufleStreamAborted", "ConectionLost"):
        assert any(repr(name) in m for m in msgs)


def test_exc_rule_accepts_real_builtin_and_repo_exceptions(tmp_path):
    files = dict(_EXC_COMMON, **{"pkg/engine.py": _EXC_GOOD})
    report = _lint(tmp_path, files, rules=["exc-contract"])
    assert _msgs(report, "exc-contract") == []


def test_exc_rule_skipped_without_rpc_module(tmp_path):
    # no RemoteError in scope → no exc_type contract to check
    report = _lint(tmp_path, {"pkg/engine.py": _EXC_BAD},
                   rules=["exc-contract"])
    assert _msgs(report, "exc-contract") == []


# ---------------------------------------------------------------------------
# real-tree mutation fences (acceptance): deleting any single registration
# from the live sources must break the fence
# ---------------------------------------------------------------------------

def _real_subtree(tmp_path, rels, mutations=()):
    """A throwaway repo holding REAL package files (mirrored paths), with
    textual mutations applied — each must match exactly once."""
    root = tmp_path / "mut"
    (root / "raydp_tpu").mkdir(parents=True)
    (root / "pyproject.toml").write_text("[project]\nname='x'\n")
    for rel in rels:
        dst = root / "raydp_tpu" / rel
        dst.parent.mkdir(parents=True, exist_ok=True)
        shutil.copyfile(os.path.join(PKG, rel), dst)
    for rel, old, new in mutations:
        p = root / "raydp_tpu" / rel
        text = p.read_text()
        assert text.count(old) >= 1, f"mutation anchor gone from {rel}: {old!r}"
        p.write_text(text.replace(old, new))
    return root


_ETL_RELS = ("etl/tasks.py", "etl/engine.py", "etl/executor.py")


def test_fence_breaks_when_patch_task_refs_branch_deleted(tmp_path):
    root = _real_subtree(tmp_path, _ETL_RELS)
    clean = run([str(root / "raydp_tpu")], root=str(root),
                rules=["step-registry"])
    assert _msgs(clean, "step-registry") == []

    root = _real_subtree(tmp_path / "b", _ETL_RELS, mutations=[
        ("etl/tasks.py", "elif isinstance(step, BroadcastJoinStep):",
         "elif False:")])
    report = run([str(root / "raydp_tpu")], root=str(root),
                 rules=["step-registry"])
    msgs = _msgs(report, "step-registry")
    assert any("BroadcastJoinStep" in m and "_patch_step_refs()" in m
               for m in msgs)


def test_fence_breaks_when_result_ref_key_unharvested(tmp_path):
    root = _real_subtree(tmp_path, _ETL_RELS, mutations=[
        ("etl/engine.py",
         '    if r.get("ref") is not None:\n        refs.append(r["ref"])\n'
         "    return refs",
         "    return refs")])
    report = run([str(root / "raydp_tpu")], root=str(root),
                 rules=["step-registry"])
    msgs = _msgs(report, "step-registry")
    assert any("'ref'" in m and "_result_refs" in m and "orphan" in m
               for m in msgs)


def test_fence_breaks_when_locality_drops_stream_buckets(tmp_path):
    root = _real_subtree(tmp_path, _ETL_RELS, mutations=[
        ("etl/engine.py",
         "elif isinstance(item, _StreamBucket):\n"
         "                    yield from item.parts_so_far()",
         "elif False:\n                    pass")])
    report = run([str(root / "raydp_tpu")], root=str(root),
                 rules=["step-registry"])
    msgs = _msgs(report, "step-registry")
    assert any("_locality()" in m and "_StreamBucket" in m for m in msgs)


_RPC_RELS = ("runtime/head.py", "runtime/object_store.py")


def test_fence_breaks_when_head_store_proxy_deleted(tmp_path):
    root = _real_subtree(tmp_path, _RPC_RELS)
    clean = run([str(root / "raydp_tpu")], root=str(root),
                rules=["rpc-surface"])
    assert _msgs(clean, "rpc-surface") == []

    root = _real_subtree(tmp_path / "b", _RPC_RELS, mutations=[
        ("runtime/head.py", "def store_lookup(self, *a):",
         "def _store_lookup_disabled(self, *a):")])
    report = run([str(root / "raydp_tpu")], root=str(root),
                 rules=["rpc-surface"])
    msgs = _msgs(report, "rpc-surface")
    assert any("'lookup'" in m and "no store_lookup proxy" in m
               for m in msgs)


def test_fence_breaks_when_contract_exception_renamed(tmp_path):
    rels = ("etl/engine.py", "runtime/rpc.py", "runtime/object_store.py")
    root = _real_subtree(tmp_path, rels)
    clean = run([str(root / "raydp_tpu")], root=str(root),
                rules=["exc-contract"])
    assert _msgs(clean, "exc-contract") == []

    root = _real_subtree(tmp_path / "b", rels, mutations=[
        ("etl/engine.py", '"ShuffleStreamAborted",',
         '"ShufleStreamAborted",')])
    report = run([str(root / "raydp_tpu")], root=str(root),
                 rules=["exc-contract"])
    msgs = _msgs(report, "exc-contract")
    assert any("'ShufleStreamAborted'" in m for m in msgs)


def test_real_rpc_call_sites_all_resolve():
    """Every literal call site in the live package resolves (the fence), and
    the surface map actually contains the load-bearing surfaces."""
    from raydp_tpu.tools.rdtlint import surfaces
    from raydp_tpu.tools.rdtlint.core import Project

    project = Project.load([PKG], root=REPO)
    smap = surfaces.build(project)
    assert "actor_ready" in smap.methods("head")
    assert smap.methods("head")["store_seal"].note \
        == "proxy → ObjectStoreServer.seal"
    assert "spawn" in smap.methods("agent")
    assert "run_function" in smap.methods("worker")
    assert smap.methods("worker")["run_function"].min_pos == 2


# ---------------------------------------------------------------------------
# CLI --json
# ---------------------------------------------------------------------------

def test_cli_json_output(tmp_path, capsys):
    bad = _repo(tmp_path, {"pkg/m.py": "import os\n"
                           "V = os.environ.get('RDT_X')\n"})
    assert rdtlint_main([str(bad / "pkg"), "--root", str(bad),
                         "--json"]) == 1
    payload = json.loads(capsys.readouterr().out)
    assert payload["files_linted"] == 1
    (v,) = payload["violations"]
    assert v["file"].endswith("m.py") and v["line"] == 2
    assert v["rule"] == "knob-registry" and "RDT_X" in v["message"]
    assert v["suppressed"] is False and v["reason"] == ""
    # clean tree → empty violations, exit 0
    capsys.readouterr()
    assert rdtlint_main([PKG, "--root", REPO, "--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["violations"] == [] and payload["suppressed"] >= 1


def test_write_rpc_docs_fails_loudly_on_missing_doc_or_markers(tmp_path,
                                                               capsys):
    # success while the drift fence keeps failing would be a trap: a wrong
    # --root or missing markers must exit 2 with the cause, not print nothing
    root = _repo(tmp_path, {"pkg/services.py": _RPC_THREE_SURFACES})
    assert rdtlint_main([str(root / "pkg"), "--root", str(root),
                         "--write-rpc-docs"]) == 2
    assert "wrong --root" in capsys.readouterr().err
    (root / "doc").mkdir()
    (root / "doc" / "dev_lint.md").write_text("# no markers here\n")
    assert rdtlint_main([str(root / "pkg"), "--root", str(root),
                         "--write-rpc-docs"]) == 2
    assert "markers" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# rule 8: telemetry-registry
# ---------------------------------------------------------------------------

_TELEMETRY_REGISTRY = """
    from dataclasses import dataclass


    @dataclass(frozen=True)
    class Metric:
        name: str
        kind: str


    @dataclass(frozen=True)
    class Span:
        name: str
        dynamic: bool = False


    @dataclass(frozen=True)
    class Event:
        kind: str


    _ALL_METRICS = [
        Metric("good_total", "counter"),
        Metric("depth_now", "gauge"),
        Metric("lat_seconds", "histogram"),
    ]
    METRICS = {m.name: m for m in _ALL_METRICS}
    _ALL_SPANS = [Span("good:span"), Span("task:", dynamic=True)]
    SPANS = {s.name: s for s in _ALL_SPANS}
    SPAN_NAMES = frozenset(s.name for s in _ALL_SPANS if not s.dynamic)
    SPAN_PREFIXES = tuple(s.name for s in _ALL_SPANS if s.dynamic)
    _ALL_EVENTS = [Event("good_event")]
    EVENTS = {e.kind: e for e in _ALL_EVENTS}
"""


def test_telemetry_rule_flags_unregistered_names_and_kind_mismatch(tmp_path):
    report = _lint(tmp_path, {
        "pkg/metrics.py": _TELEMETRY_REGISTRY,
        "pkg/user.py": """
            from raydp_tpu import metrics, profiler


            def f(dyn):
                with profiler.trace("good:span"):
                    pass
                with profiler.trace("task:Whatever"):  # dynamic family
                    pass
                with profiler.trace(f"task:{dyn}"):    # f-string: skipped
                    pass
                with profiler.trace("bad:span"):
                    pass
                metrics.inc("good_total")
                metrics.set_gauge("depth_now", 2)
                metrics.observe("lat_seconds", 1.0)
                metrics.inc("lat_seconds")
                metrics.inc("missing_total")
                metrics.record_event("good_event")
                metrics.record_event("bad_event")
        """,
    }, rules=["telemetry-registry"])
    msgs = _msgs(report, "telemetry-registry")
    assert any("'bad:span'" in m and "not declared" in m for m in msgs)
    assert any("'missing_total'" in m for m in msgs)
    assert any("'lat_seconds'" in m and "histogram" in m
               and "counter" in m for m in msgs)
    assert any("'bad_event'" in m for m in msgs)
    assert len(msgs) == 4  # the registered/dynamic/f-string uses are clean


def test_telemetry_rule_flags_dead_registry_entries(tmp_path):
    report = _lint(tmp_path, {
        "pkg/metrics.py": _TELEMETRY_REGISTRY,
        "pkg/user.py": """
            from raydp_tpu import metrics


            def f():
                metrics.inc("good_total")
        """,
    }, rules=["telemetry-registry"])
    msgs = _msgs(report, "telemetry-registry")
    for dead in ("'good:span'", "'depth_now'", "'lat_seconds'",
                 "'good_event'"):
        assert any(dead in m and "no linted code references" in m
                   for m in msgs), (dead, msgs)
    assert not any("'good_total'" in m for m in msgs)


def test_telemetry_rule_skipped_without_registry(tmp_path):
    report = _lint(tmp_path, {
        "pkg/user.py": """
            from raydp_tpu import profiler


            def f():
                with profiler.trace("anything:goes"):
                    pass
        """,
    }, rules=["telemetry-registry"])
    assert _msgs(report, "telemetry-registry") == []


def test_fence_breaks_when_span_literal_renamed(tmp_path):
    """The acceptance mutation fence: renaming ONE literal span name in the
    live tree must break the telemetry fence (the registered name becomes
    dead telemetry)."""
    root = tmp_path / "mut"
    shutil.copytree(PKG, root / "raydp_tpu",
                    ignore=shutil.ignore_patterns("__pycache__"))
    (root / "pyproject.toml").write_text("[project]\nname='x'\n")
    clean = run([str(root / "raydp_tpu")], root=str(root),
                rules=["telemetry-registry"])
    assert _msgs(clean, "telemetry-registry") == []

    ex = root / "raydp_tpu" / "etl" / "executor.py"
    text = ex.read_text()
    assert text.count('"shuffle:bucket"') == 1
    ex.write_text(text.replace('"shuffle:bucket"', '"shuffle:buckety"'))
    report = run([str(root / "raydp_tpu")], root=str(root),
                 rules=["telemetry-registry"])
    msgs = _msgs(report, "telemetry-registry")
    assert any("'shuffle:bucket'" in m and "no linted code references" in m
               for m in msgs), msgs


def test_fence_breaks_when_telemetry_doc_table_stale(tmp_path, capsys):
    """Doc drift + the --write-docs roundtrip: a hand-edited generated
    table is a violation until `python -m raydp_tpu.metrics --write-docs`
    regenerates it."""
    root = tmp_path / "mut"
    shutil.copytree(PKG, root / "raydp_tpu",
                    ignore=shutil.ignore_patterns("__pycache__"))
    (root / "pyproject.toml").write_text("[project]\nname='x'\n")
    (root / "doc").mkdir()
    shutil.copyfile(os.path.join(REPO, "doc", "observability.md"),
                    root / "doc" / "observability.md")
    clean = run([str(root / "raydp_tpu")], root=str(root),
                rules=["telemetry-registry"])
    assert _msgs(clean, "telemetry-registry") == []

    doc = root / "doc" / "observability.md"
    doc.write_text(doc.read_text().replace(
        "| `store_ops_total` |", "| `store_ops_totally` |"))
    report = run([str(root / "raydp_tpu")], root=str(root),
                 rules=["telemetry-registry"])
    assert any("stale" in m and "raydp_tpu.metrics --write-docs" in m
               for m in _msgs(report, "telemetry-registry"))

    from raydp_tpu.metrics import main as metrics_main
    assert metrics_main(["--write-docs", "--root", str(root)]) == 0
    assert "rewrote" in capsys.readouterr().out
    report = run([str(root / "raydp_tpu")], root=str(root),
                 rules=["telemetry-registry"])
    assert _msgs(report, "telemetry-registry") == []
