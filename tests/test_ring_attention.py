"""Ring attention correctness against dense attention on a seq-sharded mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raydp_tpu.ops.ring_attention import (
    dense_attention, ring_attention_sharded,
)
from raydp_tpu.parallel import MeshSpec, make_mesh


def _qkv(b=2, t=64, h=4, d=8, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.normal(size=(b, t, h, d)), jnp.float32)
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [True, False])
def test_ring_matches_dense_seq4(causal):
    mesh = make_mesh(MeshSpec(data=2, seq=4))
    q, k, v = _qkv()
    out_ring = ring_attention_sharded(q, k, v, mesh, causal=causal)
    out_dense = dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out_ring), np.asarray(out_dense),
                               atol=2e-5, rtol=2e-5)


def test_ring_full_seq8():
    mesh = make_mesh(MeshSpec(data=1, seq=8))
    q, k, v = _qkv(b=1, t=128, h=2, d=16, seed=3)
    out_ring = ring_attention_sharded(q, k, v, mesh, causal=True)
    out_dense = dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out_ring), np.asarray(out_dense),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("chunk", [4, 5])      # 5 does not divide 16: ragged
@pytest.mark.parametrize("causal", [True, False])
def test_ring_chunked_matches_dense(causal, chunk):
    """chunk_size smaller than the local block: the inner k-chunk scan (the
    pod-scale memory bound) and the causal step skip must not change the
    math — 16 rows/device folded a few keys at a time, including a ragged
    (padded + masked) final chunk."""
    mesh = make_mesh(MeshSpec(data=2, seq=4))
    q, k, v = _qkv(b=2, t=64, h=2, d=16, seed=7)
    out_ring = ring_attention_sharded(q, k, v, mesh, causal=causal,
                                      chunk_size=chunk)
    out_dense = dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out_ring), np.asarray(out_dense),
                               atol=2e-5, rtol=2e-5)


def test_ring_chunked_grad_matches_dense():
    mesh = make_mesh(MeshSpec(data=2, seq=4))
    q, k, v = _qkv(b=2, t=32, h=2, d=8, seed=9)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention_sharded(q, k, v, mesh,
                                              chunk_size=4) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v) ** 2)

    g_ring = jax.grad(loss_ring)(q, k, v)
    g_dense = jax.grad(loss_dense)(q, k, v)
    np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_dense),
                               atol=5e-4, rtol=5e-4)


def test_ring_grad_flows():
    mesh = make_mesh(MeshSpec(data=1, seq=8))
    q, k, v = _qkv(b=1, t=64, h=2, d=8)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention_sharded(q, k, v, mesh) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v) ** 2)

    g_ring = jax.grad(loss_ring)(q, k, v)
    g_dense = jax.grad(loss_dense)(q, k, v)
    np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_dense),
                               atol=5e-4, rtol=5e-4)
