"""Actor runtime + object store tests.

Parity with the reference's cluster tests (test_spark_cluster.py): actor creation
with resources, named lookup, restart-on-crash vs deliberate kill, placement-group
strategies incl. leak check, node removal fault injection, object ownership.
"""

import time

import pyarrow as pa
import pytest


class Counter:
    def __init__(self, start=0):
        self.value = start

    def incr(self, by=1):
        self.value += by
        return self.value

    def get(self):
        return self.value

    def whoami(self):
        from raydp_tpu.runtime import current_actor_context
        ctx = current_actor_context()
        return {"name": ctx.name, "restart_count": ctx.restart_count,
                "was_restarted": ctx.was_restarted}

    def crash(self):
        import os
        os._exit(17)

    def put_table(self, n):
        from raydp_tpu.runtime.object_store import get_client
        table = pa.table({"x": list(range(n))})
        return get_client().put(table)


def test_method_dispatcher_unknown_method_lists_surface():
    """A typo'd remote call fails with the target's sorted remote surface in
    the message — actionable from inside the RemoteError a driver sees —
    while underscore methods stay refused without leaking the surface."""
    from raydp_tpu.runtime.rpc import MethodDispatcher

    dispatch = MethodDispatcher(Counter())
    assert dispatch("incr", (), {}) == 1
    with pytest.raises(AttributeError) as ei:
        dispatch("inrc", (), {})
    msg = str(ei.value)
    assert "Counter has no remote method 'inrc'" in msg
    assert "remote surface: crash, get, incr, put_table, whoami" in msg
    with pytest.raises(AttributeError) as ei:
        dispatch("_private", (), {})
    assert "not remotely callable" in str(ei.value)
    assert "remote surface" not in str(ei.value)


def test_object_store_roundtrip(runtime):
    client = runtime.store_client
    ref = client.put({"a": 1, "b": [1, 2, 3]})
    assert client.get(ref) == {"a": 1, "b": [1, 2, 3]}

    table = pa.table({"x": [1, 2, 3], "y": ["a", "b", "c"]})
    tref = client.put(table)
    assert tref.kind == "arrow"
    out = client.get(tref)
    assert out.equals(table)

    assert client.contains(tref)
    client.free([ref, tref])
    assert not client.contains(tref)


def test_actor_basic_call(runtime):
    h = runtime.create_actor(Counter, (5,), name="counter")
    assert h.call("get") == 5
    assert h.incr(3) == 8
    info = h.whoami()
    assert info["name"] == "counter"
    assert info["restart_count"] == 0

    # named lookup from the registry (parity: ray.get_actor)
    h2 = runtime.get_actor("counter")
    assert h2 is not None
    assert h2.get() == 8


def test_actor_submit_future(runtime):
    h = runtime.create_actor(Counter, name="fut-counter")
    futs = [h.submit("incr", 1) for _ in range(10)]
    results = sorted(f.result(timeout=30) for f in futs)
    assert results == list(range(1, 11))


def test_actor_restart_on_crash(runtime):
    h = runtime.create_actor(Counter, (1,), name="phoenix", max_restarts=-1)
    assert h.get() == 1
    with pytest.raises(Exception):
        h.call("crash")
    # supervisor revives it; handle re-resolves; state is fresh (restart replays init)
    deadline = time.time() + 30
    while time.time() < deadline:
        try:
            if h.get() == 1:
                break
        except Exception:
            time.sleep(0.2)
    info = h.whoami()
    assert info["was_restarted"] is True
    assert info["restart_count"] >= 1


def test_actor_deliberate_kill_no_restart(runtime):
    h = runtime.create_actor(Counter, name="victim", max_restarts=-1)
    assert h.get() == 0
    h.kill(no_restart=True)
    deadline = time.time() + 15
    while time.time() < deadline and h.state() != "DEAD":
        time.sleep(0.1)
    assert h.state() == "DEAD"
    assert runtime.get_actor("victim") is None


def test_actor_object_ownership_sweep(runtime):
    h = runtime.create_actor(Counter, name="owner-actor")
    ref = h.put_table(100)
    client = runtime.store_client
    assert client.get(ref).num_rows == 100
    # transfer ownership to driver, then kill the actor: object must survive
    ref2 = h.put_table(50)
    client.transfer_ownership([ref2], "__driver__")
    h.kill(no_restart=True)
    deadline = time.time() + 15
    while time.time() < deadline and h.state() != "DEAD":
        time.sleep(0.1)
    time.sleep(0.3)
    assert not client.contains(ref)      # swept with its dead owner
    assert client.get(ref2).num_rows == 50  # survived via ownership transfer


def test_fractional_cpu_resources(runtime):
    # parity: fractional-CPU actors (test_spark_cluster.py:42-87)
    h1 = runtime.create_actor(Counter, name="frac1", resources={"CPU": 0.5})
    h2 = runtime.create_actor(Counter, name="frac2", resources={"CPU": 0.5})
    assert h1.get() == 0 and h2.get() == 0


def test_placement_group_strategies(runtime_3nodes):
    rt = runtime_3nodes
    rm = rt.resource_manager

    spread = rm.create_group([{"CPU": 1.0}] * 3, "STRICT_SPREAD")
    nodes = {b.node_id for b in spread.bundles}
    assert len(nodes) == 3

    pack = rm.create_group([{"CPU": 1.0}] * 2, "STRICT_PACK")
    assert len({b.node_id for b in pack.bundles}) == 1

    with pytest.raises(ValueError):
        rm.create_group([{"CPU": 1.0}] * 4, "STRICT_SPREAD")  # only 3 nodes

    # leak check (parity: test_spark_cluster.py:219-259 pg table leak check)
    rm.remove_group(spread.group_id)
    rm.remove_group(pack.group_id)
    assert rm.groups() == []
    for n in rm.nodes():
        assert n.available["CPU"] == n.resources["CPU"]


def test_placement_group_tpu_host_granular(runtime_3nodes):
    with pytest.raises(ValueError):
        runtime_3nodes.resource_manager.create_group([{"TPU": 0.5}], "PACK")


def test_node_affinity(runtime_3nodes):
    # parity: node affinity by custom resource (test_spark_cluster.py:90-110)
    h = runtime_3nodes.create_actor(Counter, name="affine",
                                    resources={"accel": 1.0})
    rec = runtime_3nodes.record(h.actor_id)
    node = runtime_3nodes.resource_manager.get_node(rec.node_id)
    assert node.resources.get("accel") == 1.0


def test_remove_node_respawns_actor(runtime_3nodes):
    rt = runtime_3nodes
    h = rt.create_actor(Counter, (9,), name="migrant", max_restarts=-1,
                        resources={"CPU": 1.0})
    rec = rt.record(h.actor_id)
    first_node = rec.node_id
    rt.remove_node(first_node)
    deadline = time.time() + 30
    ok = False
    while time.time() < deadline:
        try:
            if h.get() == 9:
                ok = True
                break
        except Exception:
            time.sleep(0.2)
    assert ok, "actor did not come back after node removal"
    assert rt.record(h.actor_id).node_id != first_node


def test_cluster_resources_satisfy(runtime_3nodes):
    from raydp_tpu.runtime import ClusterResources

    cr = ClusterResources(runtime_3nodes)
    cr.refresh_interval = 0.0  # no caching inside the test
    assert cr.total_alive_nodes() == 3
    # every node has 4 CPUs; the num_cpus alias maps to CPU
    assert len(cr.satisfy({"num_cpus": 4})) == 3
    assert cr.satisfy({"CPU": 5}) == []
    # only one node carries the custom accelerator resource
    assert len(cr.satisfy({"accel": 1.0})) == 1
    # allocation shrinks availability: take 3 CPUs on some node
    node_id = runtime_3nodes.resource_manager.allocate({"CPU": 3.0})
    assert node_id is not None
    assert len(cr.satisfy({"num_cpus": 4})) == 2
    labels = cr.satisfy({"CPU": 1})
    assert all(lbl.startswith("node:") for lbl in labels)


def test_driver_heartbeat_reap_sweeps_actors_and_objects(runtime):
    """A driver that stops heartbeating without detaching is reaped: its
    still-bound actors die AND the objects those actors own are swept from
    the store; a driver re-attaching under the same id afterwards is a fresh
    registration (heartbeats accepted, new actors reapable). In-process twin
    of the subprocess test in test_attach.py, covering the object sweep."""
    import uuid

    from raydp_tpu.runtime.actor import ActorSpec, dump_spec

    rt = runtime
    rt.driver_reap_after_s = 3600.0  # wide during setup; shrunk below
    rt.register_driver("hb-driver")
    assert rt.driver_heartbeat("hb-driver") is True

    cls_bytes, args_bytes = dump_spec(Counter, (3,), {})
    spec = ActorSpec(actor_id=f"actor-{uuid.uuid4().hex[:12]}",
                     name="hb-actor", cls_bytes=cls_bytes,
                     args_bytes=args_bytes, resources={"CPU": 1.0},
                     max_restarts=-1)
    h = rt.launch_actor(spec, block=True, driver_id="hb-driver")
    ref = h.put_table(25)  # owned by the actor ("hb-actor")
    assert rt.store_client.contains(ref)

    # stop heartbeating: shrink the window so the last beat lapses — the
    # supervisor kills the actor (deliberate, no restart despite
    # max_restarts=-1) and the DEAD transition frees the objects it owned
    assert rt.driver_heartbeat("hb-driver") is True  # last beat
    rt.driver_reap_after_s = 1.0
    deadline = time.time() + 30
    while time.time() < deadline and h.state() != "DEAD":
        time.sleep(0.1)
    assert h.state() == "DEAD", "reap never killed the driver's actor"
    deadline = time.time() + 10
    while time.time() < deadline and rt.store_client.contains(ref):
        time.sleep(0.1)
    assert not rt.store_client.contains(ref), \
        "dead driver's actor-owned object leaked"
    # a lapsed driver's beats are rejected (it must re-attach)...
    assert rt.driver_heartbeat("hb-driver") is False

    # ...and re-attaching with the SAME id is a clean fresh registration
    rt.driver_reap_after_s = 3600.0  # back to a sane window for the re-attach
    rt.register_driver("hb-driver")
    assert rt.driver_heartbeat("hb-driver") is True
    spec2 = ActorSpec(actor_id=f"actor-{uuid.uuid4().hex[:12]}",
                      name="hb-actor-2", cls_bytes=cls_bytes,
                      args_bytes=args_bytes, resources={"CPU": 1.0})
    h2 = rt.launch_actor(spec2, block=True, driver_id="hb-driver")
    assert h2.call("get") == 3
    rt.detach_driver("hb-driver")


class SlowInit:
    """Actor whose __init__ stalls: its ready event fires only after SLEEP_S."""
    SLEEP_S = 8.0

    def __init__(self):
        time.sleep(self.SLEEP_S)

    def ok(self):
        return True


def test_ready_waiters_do_not_starve_dispatcher(runtime):
    """20 concurrent wait_actor_ready calls on a slow-starting actor must not
    park the head's 16-thread RPC pool: an unrelated store lookup issued while
    they wait has to return immediately (VERDICT r2 weak #4 — deferred replies
    instead of blocking Event.wait in dispatcher threads)."""
    from raydp_tpu.runtime.rpc import RpcClient

    rt = runtime
    h = rt.create_actor(SlowInit, name="slowpoke", block=False)
    clients = [RpcClient(rt.server.address) for _ in range(4)]
    try:
        futs = [clients[i % 4].submit("wait_actor_ready", h.actor_id, 60.0)
                for i in range(20)]
        time.sleep(0.5)  # all 20 are registered at the head, none resolved
        assert not any(f.done() for f in futs)

        t0 = time.monotonic()
        stats = clients[0].call("store_stats", timeout=5.0)
        elapsed = time.monotonic() - t0
        assert isinstance(stats, dict)
        assert elapsed < 2.0, f"store lookup starved for {elapsed:.1f}s"

        # and the waiters still complete once the actor reports ready
        for f in futs:
            assert f.result(timeout=60.0) is True
        assert h.ok()
    finally:
        for c in clients:
            c.close()
