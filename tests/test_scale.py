"""Elastic executor pool (ISSUE 13): graceful drain, elastic membership,
restart re-admission, and the autoscale controller.

Units run against stub executor handles (no runtime) and pin the
driver-side contracts: a draining executor takes no new dispatch, a member
added mid-stage is used at once, pool-wide busy/queued signals reconcile,
and ``retire_executor`` runs drain → re-home → remove → reap in order.
Integration legs run real sessions; the chaos composition (scale-down
racing recovery / pipelined streams / serving) lives in tests/test_chaos.py.
"""

import threading
import time
from types import SimpleNamespace

import numpy as np
import pandas as pd
import pytest

from raydp_tpu import metrics
from raydp_tpu.etl.engine import Engine, ExecutorPool

from tests.test_scheduler import StubExecutor, _payloads, _tasks


# ==== elastic membership units ================================================

def test_draining_executor_gets_no_new_dispatch(monkeypatch):
    monkeypatch.setenv("RDT_SPECULATION", "0")
    a = StubExecutor(name="a")
    b = StubExecutor(name="b")
    pool = ExecutorPool([a, b])
    assert pool.begin_drain("a")
    out = pool.run_tasks(_tasks(4), payloads=_payloads(4))
    assert all(r is not None for r in out)
    assert len(a.submits) == 0, "draining executor received new work"
    assert len(b.submits) == 4
    # draining is also invisible to locality preference
    pool.cancel_drain("a")
    pool.begin_drain("a")
    pool.run_tasks(_tasks(2), preferred=["a", "a"], payloads=_payloads(2))
    assert len(a.submits) == 0


def test_begin_drain_refuses_last_live_executor():
    a = StubExecutor(name="a")
    b = StubExecutor(name="b")
    pool = ExecutorPool([a, b])
    assert pool.begin_drain("a")
    with pytest.raises(ValueError):
        pool.begin_drain("b")
    # and double-drain of one executor is a no-op, not an error
    assert pool.begin_drain("a") is False


def test_add_executor_mid_stage_is_dispatched(monkeypatch):
    """Membership is read per dispatch pass: an executor the autoscaler
    admits while a stage is running absorbs queued tasks immediately."""
    monkeypatch.setenv("RDT_SPECULATION", "0")
    slow = StubExecutor(name="slow", latency=0.15)
    pool = ExecutorPool([slow])
    fast = StubExecutor(name="fast", latency=0.005)
    done = {}

    def run():
        done["out"] = pool.run_tasks(_tasks(8), max_inflight_per_executor=1,
                                     payloads=_payloads(8))

    t = threading.Thread(target=run)
    t.start()
    time.sleep(0.05)
    pool.add_executor(fast)
    t.join(timeout=30)
    assert not t.is_alive()
    assert all(r is not None for r in done["out"])
    assert len(fast.submits) >= 3, "mid-stage member was never dispatched"


def test_remove_executor_mid_flight_retries_on_survivor(monkeypatch):
    """An abrupt removal (no drain) leaves in-flight attempts failing; the
    retry machinery lands them on the surviving member."""
    monkeypatch.setenv("RDT_SPECULATION", "0")
    from raydp_tpu.runtime.rpc import ConnectionLost

    a = StubExecutor(name="a")
    a.script = [(0.05, lambda fut: fut.set_exception(
        ConnectionLost("killed mid-flight")))] * 2
    b = StubExecutor(name="b")
    pool = ExecutorPool([a, b])
    removed = {}

    def run():
        removed["out"] = pool.run_tasks(_tasks(4),
                                        max_inflight_per_executor=2,
                                        payloads=_payloads(4))

    t = threading.Thread(target=run)
    t.start()
    time.sleep(0.02)
    assert pool.remove_executor("a") is a
    t.join(timeout=30)
    assert not t.is_alive()
    assert all(r is not None for r in removed["out"])
    assert pool.by_name.get("a") is None
    assert [h.name for h in pool.executors] == ["b"]


def test_pool_busy_and_demand_reconcile(monkeypatch):
    """load() exposes the autoscaler's signals and every exit path of
    run_tasks reconciles them back to zero."""
    monkeypatch.setenv("RDT_SPECULATION", "0")
    slow = StubExecutor(name="slow", latency=0.2)
    pool = ExecutorPool([slow])
    seen = {}

    def run():
        pool.run_tasks(_tasks(6), max_inflight_per_executor=2,
                       payloads=_payloads(6))

    t = threading.Thread(target=run)
    t.start()
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        load = pool.load()
        if load["busy"] > 0 and load["queued"] > 0:
            seen["load"] = load
            break
        time.sleep(0.01)
    t.join(timeout=30)
    assert seen, "never observed a busy+queued pool mid-stage"
    assert seen["load"]["busy"] <= 2
    assert seen["load"]["queued"] >= 1
    after = pool.load()
    assert after["busy"] == 0 and after["queued"] == 0, after
    assert pool.wait_idle("slow", timeout=1.0)


def test_mark_up_readmission_symmetry():
    """A down-marked executor that answers again re-enters placement at
    once, with the executor_up flight-recorder event mirroring the
    executor_down it balances (the restarted-mid-action re-admission)."""
    metrics.reset()
    a = StubExecutor(name="a")
    pool = ExecutorPool([a, StubExecutor(name="b")])
    ident = pool._idents[0]
    pool._mark_down(ident, "a")
    assert pool._is_down(ident)
    pool._mark_up(ident, "a")
    assert not pool._is_down(ident)
    pool._mark_up(ident, "a")  # idempotent: no second event
    kinds = [e["kind"] for e in metrics.events()]
    assert kinds.count("executor_down") == 1
    assert kinds.count("executor_up") == 1
    snap = metrics.snapshot()["counters"]
    assert snap["sched_executor_up_total"] == {"a": 1}


def test_down_executor_readmitted_within_action(monkeypatch):
    """Satellite: a restarting executor whose submits fail is marked down,
    but once its address answers again the SAME stage routes work back to
    it instead of finishing the action on the shrunken remainder."""
    monkeypatch.setenv("RDT_SPECULATION", "0")
    metrics.reset()
    a = StubExecutor(name="a")
    a.script = ["connlost"]  # first submit refused (restart in flight)
    b = StubExecutor(name="b", latency=0.3)
    pool = ExecutorPool([a, b])
    # drop the down TTL so the restarted executor is probed inside this
    # stage rather than 10s later
    monkeypatch.setattr("raydp_tpu.etl.engine._DOWN_TTL_S", 0.2)
    out = pool.run_tasks(_tasks(6), max_inflight_per_executor=1,
                         payloads=_payloads(6))
    assert all(r is not None for r in out)
    assert len(a.submits) >= 1, "restarted executor was never re-admitted"
    kinds = [e["kind"] for e in metrics.events()]
    assert "executor_down" in kinds and "executor_up" in kinds


# ==== retire_executor (drain protocol) units =================================

def _engine(pool):
    return Engine(pool, shuffle_partitions=4)


def test_retire_executor_drain_rehome_reap_order(monkeypatch):
    monkeypatch.setenv("RDT_SPECULATION", "0")
    a = StubExecutor(name="a")
    b = StubExecutor(name="b")
    pool = ExecutorPool([a, b])
    eng = _engine(pool)
    calls = []
    out = eng.retire_executor(
        "a",
        rehome=lambda name: calls.append(("rehome", name)) or 7,
        reap=lambda h: calls.append(("reap", h.name)))
    assert calls == [("rehome", "a"), ("reap", "a")]
    assert out == {"executor": "a", "quiesced": True, "rehomed": 7,
                   "pool_size": 1}
    assert [h.name for h in pool.executors] == ["b"]
    with pytest.raises(KeyError):
        eng.retire_executor("a")


def test_retire_executor_rehome_knob_off(monkeypatch):
    monkeypatch.setenv("RDT_DRAIN_REHOME", "0")
    pool = ExecutorPool([StubExecutor(name="a"), StubExecutor(name="b")])
    eng = _engine(pool)
    calls = []
    out = eng.retire_executor("a", rehome=lambda n: calls.append(n) or 3)
    assert calls == [], "RDT_DRAIN_REHOME=0 still re-homed"
    assert out["rehomed"] == 0


def test_retire_executor_waits_for_inflight(monkeypatch):
    """The drain quiesce point: retire blocks until the victim's in-flight
    task completes (pool-wide busy hits zero), and the task's result is
    kept — drained, never dropped."""
    monkeypatch.setenv("RDT_SPECULATION", "0")
    slow = StubExecutor(name="slow", latency=0.4)
    fast = StubExecutor(name="fast")
    pool = ExecutorPool([slow, fast])
    eng = _engine(pool)
    done = {}

    def run():
        done["out"] = pool.run_tasks(_tasks(1), preferred=["slow"],
                                     payloads=_payloads(1))

    t = threading.Thread(target=run)
    t.start()
    time.sleep(0.1)  # the task is in flight on `slow`
    t0 = time.monotonic()
    out = eng.retire_executor("slow")
    assert out["quiesced"] is True
    assert time.monotonic() - t0 >= 0.2, "drain did not wait for in-flight"
    t.join(timeout=10)
    assert done["out"][0] is not None


def test_retire_executor_failed_rehome_abandons(monkeypatch):
    """A re-home failure degrades to abandonment (lineage rebuilds on
    read), never fails the retirement."""
    pool = ExecutorPool([StubExecutor(name="a"), StubExecutor(name="b")])
    eng = _engine(pool)

    def boom(name):
        raise RuntimeError("re-home exploded")

    out = eng.retire_executor("a", rehome=boom)
    assert out["rehomed"] == 0
    assert [h.name for h in pool.executors] == ["b"]


def test_retire_last_executor_refused():
    pool = ExecutorPool([StubExecutor(name="only")])
    eng = _engine(pool)
    with pytest.raises(ValueError):
        eng.retire_executor("only")
    # the refusal leaves it dispatchable
    assert pool.run_tasks(_tasks(1), payloads=_payloads(1))[0] is not None


def test_retire_records_drain_event_and_counters():
    metrics.reset()
    pool = ExecutorPool([StubExecutor(name="a"), StubExecutor(name="b")])
    _engine(pool).retire_executor("a")
    kinds = [e["kind"] for e in metrics.events()]
    assert "executor_drain" in kinds
    snap = metrics.snapshot()
    assert snap["counters"]["pool_drains_total"] == {"": 1}
    assert snap["gauges"]["pool_size"] == {"": 1}


# ==== autoscale controller units =============================================

class _FakeSession:
    """Session stand-in the controller drives: grow/shrink calls recorded,
    a real ExecutorPool supplies load()."""

    def __init__(self, pool):
        self.engine = SimpleNamespace(pool=pool)
        self.grown = 0
        self.retired = []

    def _grow_executor(self):
        h = StubExecutor(name=f"new-{self.grown}")
        self.grown += 1
        self.engine.pool.add_executor(h)
        return h

    def _shrink_candidate(self):
        names = [h.name for h in self.engine.pool.executors]
        return names[-1] if len(names) > 1 else None

    def retire_executor(self, name):
        self.retired.append(name)
        self.engine.pool.remove_executor(name)


def _autoscaler(sess, **kw):
    from raydp_tpu.etl.autoscale import PoolAutoscaler
    return PoolAutoscaler(sess, **kw)


def test_autoscaler_grows_on_sustained_queue(monkeypatch):
    monkeypatch.setenv("RDT_POOL_SCALE_UP_S", "0")
    monkeypatch.setenv("RDT_POOL_COOLDOWN_S", "0")
    pool = ExecutorPool([StubExecutor(name="e0")])
    sess = _FakeSession(pool)
    auto = _autoscaler(sess, min_size=1, max_size=3)
    pool._demand_delta(5)  # queued demand, nothing in flight
    auto._tick()  # window (0s) satisfied at once: grow
    assert sess.grown == 1
    assert [e["direction"] for e in auto.events] == ["up"]
    assert len(pool.executors) == 2
    pool._demand_delta(-5)


def test_autoscaler_spike_does_not_thrash(monkeypatch):
    """Hysteresis: a queue spike shorter than RDT_POOL_SCALE_UP_S never
    grows, and after a scale event the cooldown blocks the next decision."""
    monkeypatch.setenv("RDT_POOL_SCALE_UP_S", "30")
    pool = ExecutorPool([StubExecutor(name="e0")])
    sess = _FakeSession(pool)
    auto = _autoscaler(sess, min_size=1, max_size=3)
    pool._demand_delta(5)
    auto._tick()
    auto._tick()
    assert sess.grown == 0, "a short spike grew the pool"
    pool._demand_delta(-5)
    # cooldown: force an event, then make the pool look grow-worthy
    monkeypatch.setenv("RDT_POOL_SCALE_UP_S", "0")
    monkeypatch.setenv("RDT_POOL_COOLDOWN_S", "60")
    auto._note("up", 1, "test")
    pool._demand_delta(5)
    auto._tick()
    auto._tick()
    assert sess.grown == 0, "cooldown was ignored"
    pool._demand_delta(-5)


def test_autoscaler_shrinks_idle_pool_to_min(monkeypatch):
    monkeypatch.setenv("RDT_POOL_IDLE_S", "0")
    monkeypatch.setenv("RDT_POOL_COOLDOWN_S", "0")
    pool = ExecutorPool([StubExecutor(name="e0"), StubExecutor(name="e1"),
                         StubExecutor(name="e2")])
    sess = _FakeSession(pool)
    auto = _autoscaler(sess, min_size=1, max_size=3)
    for _ in range(6):
        auto._tick()
    assert sess.retired == ["e2", "e1"]
    assert len(pool.executors) == 1, "shrank past min or not at all"


def test_autoscaler_respects_max(monkeypatch):
    monkeypatch.setenv("RDT_POOL_SCALE_UP_S", "0")
    monkeypatch.setenv("RDT_POOL_COOLDOWN_S", "0")
    pool = ExecutorPool([StubExecutor(name="e0")])
    sess = _FakeSession(pool)
    auto = _autoscaler(sess, min_size=1, max_size=2)
    pool._demand_delta(50)
    for _ in range(6):
        auto._tick()
    assert len(pool.executors) == 2, "grew past max"
    pool._demand_delta(-50)


def test_autoscaler_requires_sane_bounds():
    pool = ExecutorPool([StubExecutor(name="e0")])
    with pytest.raises(ValueError):
        _autoscaler(_FakeSession(pool))  # RDT_POOL_MAX default 0 < min


# ==== live integration =======================================================

def _ipc_bytes(table):
    import pyarrow as pa
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, table.schema) as w:
        w.write_table(table)
    return sink.getvalue().to_pybytes()


def test_session_retire_executor_live():
    """End-to-end drain on a real 3-executor session: persisted blocks
    re-home onto survivors, results stay byte-identical, the store ends at
    its pre-drain object count, and the process is reaped."""
    import raydp_tpu
    from raydp_tpu.etl import functions as F
    from raydp_tpu.runtime.object_store import get_client

    s = raydp_tpu.init("scale-retire", num_executors=3, executor_cores=1,
                       executor_memory="512MB")
    try:
        rng = np.random.RandomState(0)
        pdf = pd.DataFrame({"k": rng.randint(0, 50, 4000),
                            "v": rng.randint(0, 1000, 4000).astype(np.int64)})
        df = s.createDataFrame(pdf, num_partitions=4)
        out = df.groupBy("k").agg(F.sum("v").alias("s"))
        base = _ipc_bytes(s.engine.collect(out._plan)
                          .sort_by([("k", "ascending")]))
        cached = df.persist()
        assert cached.count() == 4000
        before = get_client().stats()["num_objects"]

        victim = s.executors[-1].name
        # the drain inventory: what the retiring executor uniquely holds
        info = s.executors[-1].call("drain_info")
        assert info["executor"] == victim
        frame = list(s._cached_frames.values())[0]
        victims_blocks = {k for k, owner in zip(frame.cache_keys,
                                                frame.executors)
                          if owner == victim}
        assert victims_blocks <= set(info["blocks"])

        size = s.retire_executor(victim)
        assert size == 2 and len(s.executors) == 2
        assert victim not in {h.name for h in s.executors}
        # no cached partition still claims the retiree (all re-homed)
        frame_id = list(s._cached_frames)[0]
        assert victim not in s._cached_frames[frame_id].executors
        # the re-homed blocks really live on the survivors
        for h in s.executors:
            for key, owner in zip(s._cached_frames[frame_id].cache_keys,
                                  s._cached_frames[frame_id].executors):
                if owner == h.name:
                    assert h.call("has_block", key)

        got = _ipc_bytes(s.engine.collect(out._plan)
                         .sort_by([("k", "ascending")]))
        assert got == base
        assert cached.count() == 4000
        assert get_client().stats()["num_objects"] == before, \
            "drain leaked store objects"
    finally:
        raydp_tpu.stop()


def test_session_autoscale_grow_and_shrink_live(monkeypatch):
    """The recorded-bench shape at test scale: a queued burst grows the
    pool within RDT_POOL_MAX, the idle window drains it back to min, and
    every action succeeds with identical results."""
    import raydp_tpu
    from raydp_tpu.etl import functions as F

    monkeypatch.setenv("RDT_POOL_SCALE_INTERVAL_S", "0.2")
    monkeypatch.setenv("RDT_POOL_SCALE_UP_S", "0.4")
    monkeypatch.setenv("RDT_POOL_IDLE_S", "1.5")
    monkeypatch.setenv("RDT_POOL_COOLDOWN_S", "1.0")
    monkeypatch.setenv("RDT_FAULTS", "executor.run_task:delay:ms=400")
    s = raydp_tpu.init("scale-auto", num_executors=1, executor_cores=1,
                       executor_memory="512MB")
    try:
        auto = s.autoscale(min_size=1, max_size=3)
        rng = np.random.RandomState(0)
        pdf = pd.DataFrame({"k": rng.randint(0, 50, 8000),
                            "v": rng.randint(0, 1000, 8000).astype(np.int64)})
        df = s.createDataFrame(pdf, num_partitions=8)
        out = df.groupBy("k").agg(F.sum("v").alias("s"))
        results, errs = [], []

        def run():
            try:
                results.append(_ipc_bytes(
                    s.engine.collect(out._plan)
                    .sort_by([("k", "ascending")])))
            except Exception as e:  # noqa: BLE001 - assert below
                errs.append(e)

        threads = [threading.Thread(target=run) for _ in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not errs, errs
        assert any(e["direction"] == "up" for e in auto.events), \
            "queued burst never grew the pool"
        deadline = time.time() + 30
        while time.time() < deadline and len(s.executors) > 1:
            time.sleep(0.3)
        assert len(s.executors) == 1, "idle pool never drained back to min"
        assert any(e["direction"] == "down" for e in auto.events)
        assert len(set(results)) == 1, "burst results diverged"
    finally:
        raydp_tpu.stop()


# ==== multi-tenant fair sharing / admission / backpressure (ISSUE 14) ========

def test_fair_gate_unit():
    """The deficit-weighted dispatch gate, driven by hand-set pool state:
    the least-served tenant always passes; a tenant past weight x the
    minimum contending share is held; no contention = no gate."""
    pool = ExecutorPool([StubExecutor(name="a")])
    # no other tenant with queued work: always allowed
    assert pool._fair_ok("flood")
    with pool._lock:
        pool._tenant_weight.update({"flood": 1.0, "inter": 1.0})
        pool._tenant_busy.update({"flood": 5, "inter": 3})
        pool._tenant_demand.update({"flood": 100, "inter": 10})
    assert not pool._fair_ok("flood"), "over-served tenant not held"
    assert pool._fair_ok("inter"), "least-served tenant was held"
    # weighted: inter at weight 3 may run 3x flood's share
    with pool._lock:
        pool._tenant_weight["inter"] = 3.0
        pool._tenant_busy.update({"flood": 2, "inter": 6})
    assert pool._fair_ok("flood") and pool._fair_ok("inter")
    with pool._lock:
        pool._tenant_busy.update({"flood": 3, "inter": 5})
    assert not pool._fair_ok("flood")
    # the contender going fully idle (demand == busy) lifts the gate
    with pool._lock:
        pool._tenant_demand["inter"] = 5
    assert pool._fair_ok("flood")


def test_fair_share_interactive_not_starved(monkeypatch):
    """A flooding tenant with hundreds of queued tasks shares the pool with
    an interactive tenant: the interactive stage's handful of tasks
    completes in bounded time instead of waiting out the flood's queue."""
    monkeypatch.setenv("RDT_SPECULATION", "0")
    pool = ExecutorPool([StubExecutor(name="e0", latency=0.01),
                         StubExecutor(name="e1", latency=0.01)])
    done = {}

    def flood():
        done["flood"] = pool.run_tasks(
            _tasks(300), max_inflight_per_executor=2,
            payloads=_payloads(300), tenant="flood")

    t = threading.Thread(target=flood)
    t.start()
    deadline = time.monotonic() + 5
    while pool.load()["queued"] < 50 and time.monotonic() < deadline:
        time.sleep(0.01)  # the flood is saturating the pool
    t0 = time.monotonic()
    out = pool.run_tasks(_tasks(8), max_inflight_per_executor=2,
                         payloads=_payloads(8), tenant="interactive")
    wall = time.monotonic() - t0
    t.join(timeout=60)
    assert all(r is not None for r in out)
    assert all(r is not None for r in done["flood"])
    # 8 tasks x 10ms on a fair half of 4 slots is ~40ms; without the gate
    # they would wait out ~300 queued flood tasks (~1.5s+)
    assert wall < 1.0, f"interactive tenant starved ({wall:.2f}s)"
    tenants = pool.load()["tenants"]
    assert tenants["interactive"]["dispatched"] == 8
    assert tenants["flood"]["busy"] == 0 and tenants["flood"]["queued"] == 0


def test_fair_share_tracks_weights(monkeypatch):
    """Two saturating tenants at weights 3:1: the observed dispatch split
    while both contend tracks the weight ratio within tolerance."""
    monkeypatch.setenv("RDT_SPECULATION", "0")
    # 16 slots: wide enough that the gate's one-task slack per tenant is
    # small against the ideal 12/4 split (at 4 slots it would dominate)
    pool = ExecutorPool([StubExecutor(name=f"e{i}", latency=0.01)
                         for i in range(4)])
    boxes = {}

    def run(tenant, weight):
        boxes[tenant] = pool.run_tasks(
            _tasks(240), max_inflight_per_executor=4,
            payloads=_payloads(240), tenant=tenant, tenant_weight=weight)

    heavy = threading.Thread(target=run, args=("heavy", 3.0))
    light = threading.Thread(target=run, args=("light", 1.0))
    heavy.start()
    light.start()
    # sample the split while BOTH tenants still have queued work
    heavy.join(timeout=120)
    at_heavy_finish = pool.load()["tenants"]
    light.join(timeout=120)
    assert all(r is not None for r in boxes["heavy"])
    assert all(r is not None for r in boxes["light"])
    h = at_heavy_finish["heavy"]["dispatched"]
    l = at_heavy_finish["light"]["dispatched"]
    assert h == 240
    # ideal split at heavy's finish: light ran 1/3 of heavy's tasks (80);
    # tolerance is generous — the contract is "tracks the ratio", not a
    # cycle-exact scheduler
    assert 0.15 <= l / h <= 0.55, f"weighted split off: heavy={h} light={l}"


def test_tenant_load_reconciles_on_every_exit_path(monkeypatch):
    """The satellite matrix: success, stage failure (abort contract),
    speculation losers, and a mid-stage abrupt removal each reconcile the
    per-tenant busy/demand maps to zero — no phantom per-tenant load."""
    from raydp_tpu.runtime.rpc import RemoteError

    def assert_clean(pool):
        load = pool.load()
        for tenant, row in load["tenants"].items():
            assert row["busy"] == 0, (tenant, load)
            assert row["demand"] == 0, (tenant, load)
        with pool._lock:
            assert pool._tenant_busy == {}, pool._tenant_busy
            assert pool._tenant_demand == {}, pool._tenant_demand
            assert pool._tenant_weight == {}, pool._tenant_weight
            assert pool._parked_by_tenant == {}

    # success path
    monkeypatch.setenv("RDT_SPECULATION", "0")
    pool = ExecutorPool([StubExecutor(name="a")])
    pool.run_tasks(_tasks(4), payloads=_payloads(4), tenant="ok")
    assert_clean(pool)

    # stage failure -> abort contract (no-retry app error)
    bad = StubExecutor(name="bad")
    bad.script = [(0.01, lambda fut: fut.set_exception(
        RemoteError("ValueError", "boom", "<tb>")))]
    pool = ExecutorPool([bad])
    with pytest.raises(Exception):
        pool.run_tasks(_tasks(3), payloads=_payloads(3), tenant="aborts")
    assert_clean(pool)

    # speculation loser: the straggler's duplicate completes AFTER the
    # stage returns; its busy decrement must still reconcile
    monkeypatch.setenv("RDT_SPECULATION", "1")
    monkeypatch.setenv("RDT_SPECULATION_QUANTILE", "0.5")
    monkeypatch.setenv("RDT_SPECULATION_MULTIPLIER", "1.1")
    monkeypatch.setenv("RDT_SPECULATION_MIN_S", "0.05")
    slow = StubExecutor(name="slow", latency=0.8)
    fast = StubExecutor(name="fast", latency=0.01)
    pool = ExecutorPool([slow, fast])
    out = pool.run_tasks(_tasks(6), max_inflight_per_executor=2,
                         payloads=_payloads(6), tenant="spec")
    assert all(r is not None for r in out)
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        with pool._lock:
            if not pool._tenant_busy:
                break
        time.sleep(0.05)  # losers land asynchronously
    assert_clean(pool)

    # mid-stage drain + abrupt removal racing a running stage
    monkeypatch.setenv("RDT_SPECULATION", "0")
    a = StubExecutor(name="a", latency=0.05)
    b = StubExecutor(name="b", latency=0.05)
    pool = ExecutorPool([a, b])
    box = {}

    def run():
        box["out"] = pool.run_tasks(_tasks(12), max_inflight_per_executor=2,
                                    payloads=_payloads(12), tenant="drain")

    t = threading.Thread(target=run)
    t.start()
    time.sleep(0.05)
    pool.begin_drain("a")
    pool.remove_executor("a")
    t.join(timeout=60)
    assert all(r is not None for r in box["out"])
    assert_clean(pool)


def test_admission_parks_then_rejects_typed(monkeypatch):
    """Over RDT_POOL_MAX_QUEUED the call parks (demand visible to the
    autoscaler) and past RDT_ADMIT_TIMEOUT_S fails with the typed no-retry
    AdmissionRejected — reconciling all load on the way out."""
    from raydp_tpu.etl.engine import AdmissionRejected

    monkeypatch.setenv("RDT_SPECULATION", "0")
    monkeypatch.setenv("RDT_POOL_MAX_QUEUED", "10")
    monkeypatch.setenv("RDT_ADMIT_TIMEOUT_S", "0.4")
    metrics.reset()
    pool = ExecutorPool([StubExecutor(name="e0", latency=0.05)])

    def flood():
        pool.run_tasks(_tasks(40), max_inflight_per_executor=2,
                       payloads=_payloads(40), tenant="flood")

    t = threading.Thread(target=flood)
    t.start()
    deadline = time.monotonic() + 5
    while pool.load()["queued"] < 11 and time.monotonic() < deadline:
        time.sleep(0.01)
    seen = {}

    def late():
        t0 = time.monotonic()
        try:
            pool.run_tasks(_tasks(4), payloads=_payloads(4), tenant="late")
        except AdmissionRejected as e:
            seen["err"] = e
            seen["wall"] = time.monotonic() - t0

    lt = threading.Thread(target=late)
    lt.start()
    time.sleep(0.1)
    load = pool.load()
    assert load["parked"] == 4, load  # parked demand is visible
    assert load["queued"] >= 11      # ... and counted in the autoscale signal
    # a PARKED tenant is not a fair-share contender: the running flood
    # keeps its full in-flight cap instead of being serialized to one
    # task for the whole park (which would also keep the backlog from
    # ever draining)
    assert load["tenants"]["flood"]["busy"] == 2, load
    assert pool._fair_ok("flood")
    lt.join(timeout=30)
    t.join(timeout=60)
    assert isinstance(seen.get("err"), AdmissionRejected), seen
    assert seen["wall"] >= 0.35
    assert_events = [e["kind"] for e in metrics.events()]
    assert "admission_reject" in assert_events
    snap = metrics.snapshot()["counters"]
    assert snap["pool_admission_parked_total"] == {"late": 1}
    assert snap["pool_admission_rejects_total"] == {"late": 1}
    with pool._lock:
        assert pool._parked_by_tenant == {}
        assert pool._tenant_demand == {}


def test_admission_empty_backlog_always_admits(monkeypatch):
    """A single action larger than the bound runs on an idle pool — the
    bound protects against a backlog, it never wedges a lone big stage."""
    monkeypatch.setenv("RDT_SPECULATION", "0")
    monkeypatch.setenv("RDT_POOL_MAX_QUEUED", "5")
    monkeypatch.setenv("RDT_ADMIT_TIMEOUT_S", "0.2")
    pool = ExecutorPool([StubExecutor(name="e0")])
    out = pool.run_tasks(_tasks(30), payloads=_payloads(30), tenant="big")
    assert all(r is not None for r in out)


def test_admission_parked_action_admitted_when_backlog_drains(monkeypatch):
    """The park is a wait, not a rejection: once the running backlog
    drains under the bound the parked action dispatches and completes."""
    monkeypatch.setenv("RDT_SPECULATION", "0")
    monkeypatch.setenv("RDT_POOL_MAX_QUEUED", "10")
    monkeypatch.setenv("RDT_ADMIT_TIMEOUT_S", "30")
    pool = ExecutorPool([StubExecutor(name="e0", latency=0.01)])

    def flood():
        pool.run_tasks(_tasks(30), max_inflight_per_executor=2,
                       payloads=_payloads(30), tenant="flood")

    t = threading.Thread(target=flood)
    t.start()
    deadline = time.monotonic() + 5
    while pool.load()["queued"] < 11 and time.monotonic() < deadline:
        time.sleep(0.005)
    out = pool.run_tasks(_tasks(4), payloads=_payloads(4), tenant="late")
    t.join(timeout=60)
    assert all(r is not None for r in out)


def test_admission_fifo_first_parked_first_admitted(monkeypatch):
    """Freed backlog admits parked actions in PARK ORDER (ROADMAP 3c):
    four actions park behind a synthetic flood; when the flood drains, they
    must admit first-parked-first — not in whichever order their poll loops
    happened to wake (the pre-FIFO race)."""
    monkeypatch.setenv("RDT_SPECULATION", "0")
    monkeypatch.setenv("RDT_POOL_MAX_QUEUED", "10")
    monkeypatch.setenv("RDT_ADMIT_TIMEOUT_S", "30")
    pool = ExecutorPool([StubExecutor(name="e0")])
    flood = 20
    with pool._lock:
        pool._demand += flood
    order = []

    def admit(tag):
        # the real callers register demand before _admit and release after
        with pool._lock:
            pool._demand += 4
        pool._admit(tag, 4)
        order.append(tag)
        with pool._lock:
            pool._demand -= 4

    threads = []
    for tag in ("first", "second", "third", "fourth"):
        t = threading.Thread(target=admit, args=(tag,))
        t.start()
        threads.append(t)
        # park order IS ticket order: wait until THIS one is parked before
        # starting the next
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            with pool._lock:
                if pool._parked_by_tenant.get(tag):
                    break
            time.sleep(0.005)
    with pool._lock:
        assert pool._park_queue == sorted(pool._park_queue)
        assert len(pool._park_queue) == 4
        pool._demand -= flood  # the flood drains: the whole backlog frees
    for t in threads:
        t.join(timeout=30)
    assert order == ["first", "second", "third", "fourth"]
    with pool._lock:
        assert pool._park_queue == []
        assert pool._parked_by_tenant == {}
        pool._demand = 0


def test_admission_fifo_newcomer_queues_behind_parked(monkeypatch):
    """A fresh arrival that WOULD fit must still queue behind an
    already-parked action instead of jumping it (first parked, first
    admitted covers admission order, not just wakeup order)."""
    monkeypatch.setenv("RDT_SPECULATION", "0")
    monkeypatch.setenv("RDT_POOL_MAX_QUEUED", "10")
    monkeypatch.setenv("RDT_ADMIT_TIMEOUT_S", "30")
    pool = ExecutorPool([StubExecutor(name="e0")])
    with pool._lock:
        pool._demand += 12   # flood: backlog 12 > 10 parks anything
    order = []

    def admit(tag, n):
        with pool._lock:
            pool._demand += n
        pool._admit(tag, n)
        order.append(tag)
        with pool._lock:
            pool._demand -= n

    big = threading.Thread(target=admit, args=("big", 8))
    big.start()
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        with pool._lock:
            if pool._parked_by_tenant.get("big"):
                break
        time.sleep(0.005)
    # drain the flood to 5: big still cannot fit (5+8 > 10) but a small
    # newcomer WOULD (5+2 <= 10) — pre-FIFO it would jump straight past
    # the parked big action; now it must park behind it
    with pool._lock:
        pool._demand -= 7
    small = threading.Thread(target=admit, args=("small", 2))
    small.start()
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        with pool._lock:
            if pool._parked_by_tenant.get("small"):
                break
        time.sleep(0.005)
    with pool._lock:
        assert pool._parked_by_tenant.get("small") == 2, \
            "the fitting newcomer jumped the parked queue"
        assert order == []
        pool._demand -= 5  # now the flood is gone: both admit, in order
    big.join(timeout=30)
    small.join(timeout=30)
    assert order == ["big", "small"]
    with pool._lock:
        assert pool._park_queue == [] and pool._parked_by_tenant == {}
        pool._demand = 0


def test_backpressure_pauses_and_resumes_dispatch(monkeypatch):
    """A host above the store high-watermark takes no dispatch until it
    drops below the low-watermark; with every host paused, tasks wait
    (graceful degradation) and complete once pressure lifts."""
    monkeypatch.setenv("RDT_SPECULATION", "0")
    metrics.reset()
    pressure = {"hostA": 2.0}
    a = StubExecutor(name="a")
    b = StubExecutor(name="b")
    pool = ExecutorPool([a, b], hosts_by_name={"a": "hostA", "b": "hostB"})
    pool.pressure_provider = lambda: dict(pressure)
    out = pool.run_tasks(_tasks(6), payloads=_payloads(6))
    assert all(r is not None for r in out)
    assert len(a.submits) == 0, "dispatched to a backpressured host"
    assert len(b.submits) == 6
    assert pool.load()["backpressured_hosts"] == ["hostA"]

    # every host over the watermark: dispatch pauses, then resumes when
    # pressure drops (the cache TTL is 0.5s; drop it via a fresh eval)
    pressure["hostB"] = 2.0
    pool._pressure_cache = None
    box = {}

    def run():
        box["out"] = pool.run_tasks(_tasks(2), payloads=_payloads(2))

    t = threading.Thread(target=run)
    t.start()
    time.sleep(0.3)
    assert "out" not in box, "dispatch proceeded under full backpressure"
    pressure.update({"hostA": 0.5, "hostB": 0.5})
    pool._pressure_cache = None
    t.join(timeout=30)
    assert all(r is not None for r in box["out"])
    kinds = [e["kind"] for e in metrics.events()]
    assert "backpressure" in kinds
    snap = metrics.snapshot()["counters"]
    assert snap["pool_backpressure_total"]["hostA"] >= 1


def test_backpressure_fails_closed_on_stats_error(monkeypatch):
    """A transient pressure-provider failure (an overloaded store head is
    exactly when stats() times out) must KEEP the previous pause state,
    never fail open and resume dispatch to an over-watermark host."""
    monkeypatch.setenv("RDT_SPECULATION", "0")
    pressure = {"hostA": 2.0}
    a = StubExecutor(name="a")
    b = StubExecutor(name="b")
    pool = ExecutorPool([a, b], hosts_by_name={"a": "hostA", "b": "hostB"})
    calls = {"n": 0}

    def provider():
        calls["n"] += 1
        if calls["n"] > 1:
            raise RuntimeError("stats timed out")
        return dict(pressure)

    pool.pressure_provider = provider
    assert pool.load()["backpressured_hosts"] == ["hostA"]  # tripped
    pool._pressure_cache = None  # force a re-evaluation: provider now fails
    out = pool.run_tasks(_tasks(4), payloads=_payloads(4))
    assert all(r is not None for r in out)
    assert len(a.submits) == 0, "stats failure fail-opened backpressure"
    assert pool.load()["backpressured_hosts"] == ["hostA"]
    assert calls["n"] >= 2


# ==== predictive sizing + parked-demand cooldown pierce (ISSUE 19) ===========


def _park(pool, n, tenant="t"):
    with pool._lock:
        pool._parked_by_tenant[tenant] = \
            pool._parked_by_tenant.get(tenant, 0) + n
    pool._demand_delta(n)


def _unpark(pool, n, tenant="t"):
    with pool._lock:
        pool._parked_by_tenant[tenant] -= n
        if pool._parked_by_tenant[tenant] <= 0:
            del pool._parked_by_tenant[tenant]
    pool._demand_delta(-n)


def test_parked_demand_pierces_cooldown(monkeypatch):
    """The post-shrink cooldown must not delay a grow when admission has
    PARKED demand: parked actions cannot run until capacity exists, so the
    hysteresis that guards against recovery spikes does not apply. One
    prior tick of parked demand is required (no same-tick double-spawn)."""
    monkeypatch.setenv("RDT_POOL_SCALE_UP_S", "30")   # window would block
    monkeypatch.setenv("RDT_POOL_COOLDOWN_S", "60")   # cooldown would block
    pool = ExecutorPool([StubExecutor(name="e0")])
    sess = _FakeSession(pool)
    auto = _autoscaler(sess, min_size=1, max_size=4)
    auto._note("down", 1, "test")   # a fresh scale event arms the cooldown
    _park(pool, 2)
    auto._tick()                    # observes parked demand (arms window)
    assert sess.grown == 0, "same-tick parked demand grew immediately"
    auto._tick()                    # prior-tick parked demand: grow NOW
    assert sess.grown == 2, "cooldown suppressed parked-demand grow"
    assert auto.events[-1]["direction"] == "up"
    assert "parked=2" in auto.events[-1]["reason"]
    _unpark(pool, 2)


def test_parked_demand_sizes_grow_predictively(monkeypatch):
    """One grow decision targets one free slot per parked admission —
    capped at the max bound — instead of stepping +1 per cooldown."""
    monkeypatch.setenv("RDT_POOL_SCALE_UP_S", "0")
    monkeypatch.setenv("RDT_POOL_COOLDOWN_S", "0")
    pool = ExecutorPool([StubExecutor(name="e0")])
    sess = _FakeSession(pool)
    auto = _autoscaler(sess, min_size=1, max_size=3)
    _park(pool, 5)
    auto._tick()
    auto._tick()
    assert len(pool.executors) == 3, "parked grow did not reach the cap"
    # the cap held: 5 parked would have wanted 6 executors
    assert sess.grown == 2
    _unpark(pool, 5)


def test_aqe_measured_bytes_size_the_pool(monkeypatch):
    """Predictive sizing from the AQE plane: with RDT_POOL_BYTES_PER_EXEC
    set, a grow decision targets ceil(measured stage bytes / knob)
    executors (a fake ledger supplies the measurement)."""
    monkeypatch.setenv("RDT_POOL_SCALE_UP_S", "0")
    monkeypatch.setenv("RDT_POOL_COOLDOWN_S", "0")
    monkeypatch.setenv("RDT_POOL_BYTES_PER_EXEC", "100")
    pool = ExecutorPool([StubExecutor(name="e0")])
    sess = _FakeSession(pool)
    sess.engine.measured_stage_bytes = lambda: 450   # -> ceil(4.5) = 5
    auto = _autoscaler(sess, min_size=1, max_size=8)
    pool._demand_delta(1)   # any queued demand triggers the decision
    auto._tick()
    assert len(pool.executors) == 5, \
        f"AQE sizing off: {len(pool.executors)} executors"
    assert "target=5" in auto.events[-1]["reason"]
    pool._demand_delta(-1)
    # without the knob the same decision steps +1
    monkeypatch.setenv("RDT_POOL_BYTES_PER_EXEC", "0")
    monkeypatch.setenv("RDT_POOL_COOLDOWN_S", "0")
    auto._cooldown_until = 0.0
    pool._demand_delta(1)
    auto._tick()
    assert len(pool.executors) == 6
    pool._demand_delta(-1)


def test_autoscaler_feeds_store_budget_derivation():
    """Every tick forwards the stage ledger's measured bytes to the store
    budget plane (Engine.derive_store_budgets) when the engine exposes it;
    bare stubs without the method are tolerated."""
    pool = ExecutorPool([StubExecutor(name="e0")])
    sess = _FakeSession(pool)
    calls = []
    sess.engine.derive_store_budgets = lambda: calls.append(1)
    auto = _autoscaler(sess, min_size=1, max_size=2)
    auto._tick()
    auto._tick()
    assert len(calls) == 2, "budget feed not driven from the tick"
