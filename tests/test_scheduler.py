"""Straggler-proof stage scheduling units (ISSUE 5): least-loaded dispatch
with per-executor in-flight caps, locality-preserving retries, per-handle
down tracking, and speculative backup tasks — first finisher wins, the
loser's outputs drain through the late-result path.

These run against stub executor handles (no runtime), so they pin the
DRIVER-side scheduling contract; the end-to-end composition with real
executors and the fault plane lives in tests/test_chaos.py and the
``--straggler`` leg of benchmarks/shuffle_bench.py.
"""

import threading
import time
from concurrent.futures import Future
from types import SimpleNamespace

import pyarrow as pa

from raydp_tpu.etl import engine as E
from raydp_tpu.etl.engine import ExecutorPool
from raydp_tpu.runtime.object_store import ObjectRef
from raydp_tpu.runtime.rpc import ConnectionLost, RemoteError


class StubExecutor:
    """Actor-handle stand-in: ``submit`` returns a Future a timer resolves
    after ``latency`` seconds. ``script`` overrides per call, in order: the
    string ``"connlost"`` raises on submit; ``(delay, fn)`` runs ``fn(fut)``
    after ``delay`` (fn=None → the default ok result)."""

    def __init__(self, name=None, actor_id=None, latency=0.005):
        self.name = name
        if actor_id is not None:
            self.actor_id = actor_id
        self.latency = latency
        self.script = []
        self.submits = []           # submit timestamps (successful only)
        self.concurrent = 0
        self.peak = 0
        self.dropped = []           # (keys, if_stamp) from drop_blocks
        self._lock = threading.Lock()

    def submit(self, method, payload):
        with self._lock:
            item = self.script.pop(0) if self.script else None
        if item == "connlost":
            raise ConnectionLost("submit refused")
        delay, fn = item if item is not None else (self.latency, None)
        fut = Future()
        with self._lock:
            self.submits.append(time.monotonic())
            self.concurrent += 1
            self.peak = max(self.peak, self.concurrent)

        def _finish():
            with self._lock:
                self.concurrent -= 1
            if fn is not None:
                fn(fut)
            else:
                fut.set_result({"num_rows": 1, "executor": self.name})

        threading.Timer(delay, _finish).start()
        return fut

    def drop_blocks(self, keys, if_stamp=None):
        self.dropped.append((list(keys), if_stamp))


def _tasks(n):
    return [SimpleNamespace(task_id=f"t{i}") for i in range(n)]


def _payloads(n):
    return [b"payload"] * n


def test_per_executor_cap_no_stacking(monkeypatch):
    """A slow executor's queue must never exceed its own cap while the fast
    sibling has free slots — the old single global ``4 × pool`` cap let the
    whole stage stack up behind one straggler."""
    monkeypatch.setenv("RDT_SPECULATION", "0")
    slow = StubExecutor(name="slow", latency=0.25)
    fast = StubExecutor(name="fast", latency=0.005)
    pool = ExecutorPool([slow, fast])
    stats = {}
    out = pool.run_tasks(_tasks(10), max_inflight_per_executor=2,
                         payloads=_payloads(10), sched_stats=stats)
    assert all(r is not None for r in out)
    assert slow.peak <= 2, "slow executor exceeded its per-executor cap"
    assert stats["per_executor_busy"]["slow"] <= 2
    # the fast executor absorbed the queue the slow one could not take
    assert len(fast.submits) >= 6, (len(slow.submits), len(fast.submits))


def test_preferred_hands_off_when_at_cap(monkeypatch):
    """Locality preference is kept — but a preferred executor whose queue is
    at cap hands the task to the least-loaded live sibling instead of
    stacking behind itself."""
    monkeypatch.setenv("RDT_SPECULATION", "0")
    a = StubExecutor(name="a", latency=0.15)
    b = StubExecutor(name="b", latency=0.005)
    pool = ExecutorPool([a, b])
    out = pool.run_tasks(_tasks(4), preferred=["a"] * 4,
                         max_inflight_per_executor=1, payloads=_payloads(4))
    assert all(r is not None for r in out)
    assert a.peak <= 1
    assert len(a.submits) >= 1          # preference honored while free
    assert len(b.submits) >= 2, "tasks stacked on the preferred executor"


def test_preferred_honored_when_below_cap(monkeypatch):
    monkeypatch.setenv("RDT_SPECULATION", "0")
    a = StubExecutor(name="a")
    b = StubExecutor(name="b")
    pool = ExecutorPool([a, b])
    pool.run_tasks(_tasks(4), preferred=["b"] * 4,
                   max_inflight_per_executor=4, payloads=_payloads(4))
    assert len(a.submits) == 0
    assert len(b.submits) == 4


def test_retry_keeps_locality(monkeypatch):
    """Satellite: a transient failure used to strand a cache-local task on
    round-robin for every later attempt — the retry must return to the
    preferred executor whenever it is not marked down."""
    monkeypatch.setenv("RDT_SPECULATION", "0")
    a = StubExecutor(name="a")
    a.script = [(0.005, lambda fut: fut.set_exception(
        RemoteError("RuntimeError", "transient boom", "tb")))]
    b = StubExecutor(name="b")
    pool = ExecutorPool([a, b])
    out = pool.run_tasks(_tasks(1), preferred=["a"], payloads=_payloads(1))
    assert out[0] is not None
    assert len(a.submits) == 2, "retry did not return to the preferred executor"
    assert len(b.submits) == 0


def test_down_map_keyed_per_handle_not_by_name(monkeypatch):
    """Satellite: executors with ``name == None`` used to share one
    ``down[""]`` entry, so one unnamed executor's crash marked every unnamed
    executor down. The down map keys on a stable per-handle identity."""
    monkeypatch.setenv("RDT_SPECULATION", "0")
    a = StubExecutor(name=None)
    a.script = ["connlost"] * 8      # permanently unreachable
    b = StubExecutor(name=None)
    pool = ExecutorPool([a, b])
    t0 = time.monotonic()
    out = pool.run_tasks(_tasks(2), payloads=_payloads(2))
    wall = time.monotonic() - t0
    assert all(r is not None for r in out)
    assert len(b.submits) == 2, "sibling unnamed executor was aliased down"
    # rotating to the live sibling is immediate — not the unreachable grace
    assert wall < 5.0, wall


def test_busy_pool_with_one_down_executor_waits_not_fails(monkeypatch):
    """Regression (review finding): when every LIVE executor is at its cap,
    queued tasks must WAIT for a slot — not probe a down executor's dead
    address and burn their unreachable grace while the pool is merely busy.
    With a 1s grace, a down executor, and a live sibling whose backlog
    exceeds that grace, the stage must still complete."""
    monkeypatch.setenv("RDT_SPECULATION", "0")
    monkeypatch.setenv("RDT_EXECUTOR_WAIT_S", "1")
    dead = StubExecutor(name="dead")
    dead.script = ["connlost"] * 64
    live = StubExecutor(name="live", latency=0.3)
    pool = ExecutorPool([dead, live])
    out = pool.run_tasks(_tasks(6), max_inflight_per_executor=1,
                         payloads=_payloads(6))
    assert all(r is not None for r in out)
    assert live.peak <= 1
    assert len(live.submits) == 6
    # the dead executor saw at most the probes from moments when NO live
    # executor existed yet (the very first fill, before it was marked down)
    assert len(dead.script) >= 56, "busy pool kept probing the dead executor"


def test_stable_idents_prefer_actor_id():
    a = StubExecutor(name=None, actor_id="actor-1")
    b = StubExecutor(name="named")
    c = StubExecutor(name=None)
    pool = ExecutorPool([a, b, c])
    idents = pool._idents
    assert idents[0] == "actor-1"
    assert idents[1] == "named"
    assert idents[2].startswith("anon-")
    assert len(set(idents)) == 3


def test_speculation_backup_wins_and_loser_drained(monkeypatch):
    """Once the stage is past the completion quantile and an attempt runs
    past the threshold, a backup of the same payload lands on a DIFFERENT
    executor; the first finisher wins, the stage does not wait for the
    straggler, and the loser's store outputs are freed when it lands."""
    monkeypatch.setenv("RDT_SPECULATION_MIN_S", "0.1")
    monkeypatch.setenv("RDT_SPECULATION_QUANTILE", "0.5")
    freed = []

    class _Client:
        def free(self, refs):
            freed.extend(r.id for r in refs)
            return len(refs)

    monkeypatch.setattr(E, "get_client", lambda: _Client())

    loser_ref = ObjectRef(id="d" * 32)

    def slow_result(fut):
        fut.set_result({"num_rows": 1, "ref": loser_ref, "executor": "slow"})

    slow = StubExecutor(name="slow")
    slow.script = [(1.5, slow_result)] * 3
    fast = StubExecutor(name="fast", latency=0.01)
    pool = ExecutorPool([slow, fast])
    stats = {}
    t0 = time.monotonic()
    out = pool.run_tasks(_tasks(6), payloads=_payloads(6), sched_stats=stats)
    wall = time.monotonic() - t0
    assert all(r is not None for r in out)
    assert wall < 1.2, f"stage waited out the straggler ({wall:.2f}s)"
    assert stats["speculated"] >= 1
    assert stats["speculation_won"] >= 1
    # winner results carry the driver-side annotations the report sums
    assert sum(int(r.get("_speculation_won", 0)) for r in out) == \
        stats["speculation_won"]
    # every backup ran on the OTHER executor (never beside its primary)
    assert len(fast.submits) >= 3 + stats["speculation_won"]
    # the losers land at ~1.5s; their blobs free through the late path
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline \
            and freed.count(loser_ref.id) < stats["speculation_won"]:
        time.sleep(0.05)
    assert freed.count(loser_ref.id) >= stats["speculation_won"], freed


def test_speculation_disabled_by_env(monkeypatch):
    monkeypatch.setenv("RDT_SPECULATION", "0")
    monkeypatch.setenv("RDT_SPECULATION_MIN_S", "0.05")
    monkeypatch.setenv("RDT_SPECULATION_QUANTILE", "0.1")
    slow = StubExecutor(name="slow")
    slow.script = [(0.6, None)] * 2
    fast = StubExecutor(name="fast", latency=0.01)
    pool = ExecutorPool([slow, fast])
    stats = {}
    t0 = time.monotonic()
    out = pool.run_tasks(_tasks(4), payloads=_payloads(4), sched_stats=stats)
    wall = time.monotonic() - t0
    assert all(r is not None for r in out)
    assert stats["speculated"] == 0
    assert stats["speculation_won"] == 0
    assert wall >= 0.5, "stage finished before its unspeculated straggler"


def test_block_cache_put_once_idempotent():
    """Executor satellite: a duplicate cache-put (speculative backup of a
    CACHE task) keeps the existing entry and reports ITS stamp, so both
    attempts' results name the same generation."""
    from raydp_tpu.etl.executor import BlockCache

    cache = BlockCache()
    t = pa.table({"a": [1]})
    assert cache.put_once("k", t, "s1") == "s1"
    assert cache.put_once("k", t, "s2") == "s1"   # kept, stamp shared
    assert cache.drop(["k"], if_stamp="s2") == 0  # the discarded stamp
    assert cache.drop(["k"], if_stamp="s1") == 1
    # plain put still overwrites (recovery recache path)
    cache.put("k", t, "s3")
    assert cache.put_once("k", t, "s4") == "s3"


def test_loser_cache_drop_skipped_when_entry_shared():
    """When both copies of a CACHE task ran on ONE executor, the idempotent
    put makes their stamps coincide — the loser drain must then leave the
    block alone (it IS the winner's block); a loser on a different executor
    still has its spurious block dropped, stamp-conditioned."""
    h = StubExecutor(name="e")
    pool = ExecutorPool.__new__(ExecutorPool)
    pool.by_name = {"e": h}

    shared = Future()
    shared.set_result({"cache_key": "k", "cache_stamp": "s", "executor": "e"})
    winner = {"cache_key": "k", "cache_stamp": "s", "executor": "e"}
    pool._free_loser_result_sync(shared, winner)
    assert h.dropped == [], "shared cache entry was dropped under the winner"

    elsewhere = Future()
    elsewhere.set_result({"cache_key": "k", "cache_stamp": "s2",
                          "executor": "e"})
    winner2 = {"cache_key": "k", "cache_stamp": "s1", "executor": "other"}
    pool._free_loser_result_sync(elsewhere, winner2)
    assert h.dropped == [(["k"], "s2")]


def test_locality_weights_total_range_bytes_across_all_parts(monkeypatch):
    """ISSUE 7 small fix: a multi-range source (a coalesced read fusing
    several buckets, or a split portion spanning maps) must be routed by the
    TOTAL bytes it reads across all its (ref, off, size) triples — not just
    wherever its first ref lives. Nested part-lists (a fused group of
    buckets) flatten into the same weighting."""
    pool = ExecutorPool([StubExecutor(name="eA"), StubExecutor(name="eB")],
                        hosts_by_name={"eA": "hostA", "eB": "hostB"})
    engine = E.Engine(pool)

    ra = ObjectRef(id="a" * 32, size=10)       # lives on hostA, small
    rb1 = ObjectRef(id="b" * 32, size=4000)    # hostB, bulk of the bytes
    rb2 = ObjectRef(id="c" * 32, size=3000)    # hostB

    class _Client:
        def locations(self, refs):
            return {("a" * 32): "hostA", ("b" * 32): "hostB",
                    ("c" * 32): "hostB"}

    monkeypatch.setattr(E, "get_client", lambda: _Client())

    # first ref on hostA, but the range bytes overwhelmingly live on hostB
    flat = [[(ra, 0, 10), (rb1, 0, 4000), (rb2, 0, 3000)]]
    assert engine._locality(flat) == ["eB"]
    # nested part-lists (a coalesced multi-bucket group) weigh the same
    nested = [[[(ra, 0, 10)], [(rb1, 0, 4000), (rb2, 0, 3000)]]]
    assert engine._locality(nested) == ["eB"]
    # plain refs still weight by whole-blob size
    assert engine._locality([[ra], [rb1]]) == ["eA", "eB"]
    # range SIZE (not the blob's) is what counts: a tiny slice of a huge
    # blob on hostB must not outweigh real bytes on hostA
    huge_b = ObjectRef(id="d" * 32, size=1 << 20)

    class _Client2(_Client):
        def locations(self, refs):
            return {("a" * 32): "hostA", ("d" * 32): "hostB"}

    monkeypatch.setattr(E, "get_client", lambda: _Client2())
    assert engine._locality([[(ra, 0, 10), (huge_b, 0, 4)]]) == ["eA"]


def test_locality_reweights_streaming_reducers_from_seals_so_far(
        monkeypatch):
    """ISSUE 8 small fix: a streaming reduce task dispatched before the map
    stage finishes used to be preference-free — its bucket has no concrete
    ranges yet. ``_locality`` now expands a ``_StreamBucket`` to the ranges
    of the seals seen SO FAR (the driver published them, so it knows), and
    a stage with no seals yet genuinely has no preference."""
    pool = ExecutorPool([StubExecutor(name="eA"), StubExecutor(name="eB")],
                        hosts_by_name={"eA": "hostA", "eB": "hostB"})
    engine = E.Engine(pool)

    ra = ObjectRef(id="a" * 32, size=5000)
    rb = ObjectRef(id="b" * 32, size=50)

    class _Client:
        def locations(self, refs):
            return {("a" * 32): "hostA", ("b" * 32): "hostB"}

    monkeypatch.setattr(E, "get_client", lambda: _Client())

    rec = E._StreamStageRec("ss-test", "repartition", num_maps=3)
    # no seals yet: genuinely preference-free
    empty = E._StreamBucket(rec, 0)
    assert engine._locality([[empty]]) == [None]
    # two of three maps sealed; bucket 0's bytes live mostly on hostA,
    # bucket 1's on hostB — each streaming reducer routes by ITS ranges
    rec.seals[0] = (ra, [(0, 4000, 10), (4000, 10, 1)])
    rec.seals[2] = (rb, [(0, 10, 1), (10, 40, 2)])
    assert engine._locality([[E._StreamBucket(rec, 0)],
                             [E._StreamBucket(rec, 1)]]) == ["eA", "eB"]
    # a join-style entry mixing a stream bucket with concrete right-side
    # ranges weighs them together
    big_b = ObjectRef(id="c" * 32, size=9000)

    class _Client2(_Client):
        def locations(self, refs):
            return {("a" * 32): "hostA", ("b" * 32): "hostB",
                    ("c" * 32): "hostB"}

    monkeypatch.setattr(E, "get_client", lambda: _Client2())
    assert engine._locality(
        [[E._StreamBucket(rec, 0), (big_b, 0, 9000)]]) == ["eB"]


# ==== data-gravity scheduling: residency tiers (ISSUE 19) ====================


def test_locality_tier_matrix(monkeypatch):
    """The data-gravity weight order: shm > spilled > remote > absent. A
    host whose copy is SPILLED counts its bytes at
    RDT_LOCALITY_SPILLED_WEIGHT (default 0.5) — between in-memory-local
    and remote — so a fault-in storm can lose to a bigger shm pile, a
    spilled-local copy still beats no copy, and weight 0 disqualifies
    spilled copies entirely."""
    pool = ExecutorPool([StubExecutor(name="eA"), StubExecutor(name="eB")],
                        hosts_by_name={"eA": "hostA", "eB": "hostB"})
    engine = E.Engine(pool)
    ra = ObjectRef(id="a" * 32, size=1000)   # shm copy on hostA
    rb = ObjectRef(id="b" * 32, size=1600)   # spilled copy on hostB

    class _Client:
        def residency(self, refs):
            return {("a" * 32): ("hostA", "shm"),
                    ("b" * 32): ("hostB", "spilled"),
                    ("c" * 32): ("hostB", "spilled")}

    monkeypatch.setattr(E, "get_client", lambda: _Client())
    # hostB holds MORE raw bytes (1600 > 1000), but spilled at 0.5 weighs
    # 800: the smaller shm pile wins
    assert engine._locality([[ra, rb]]) == ["eA"]
    # enough spilled bytes still win: 0.5 x 2400 = 1200 > 1000
    rc = ObjectRef(id="c" * 32, size=2400)
    assert engine._locality([[ra, rc]]) == ["eB"]
    # spilled-local beats remote/absent: the only copy is hostB's disk
    assert engine._locality([[rb]]) == ["eB"]
    # absent bytes weigh nothing: no residency entry, no preference
    rz = ObjectRef(id="f" * 32, size=9999)
    assert engine._locality([[rz]]) == [None]
    # weight 0 makes a spilled copy indistinguishable from absent
    monkeypatch.setenv("RDT_LOCALITY_SPILLED_WEIGHT", "0")
    assert engine._locality([[rb]]) == [None]


def test_locality_tier_tie_rotation(monkeypatch):
    """Hosts tied on weight rotate deterministically across picks instead
    of always landing on the first-sorted host — tied placements spread."""
    pool = ExecutorPool([StubExecutor(name="eA"), StubExecutor(name="eB")],
                        hosts_by_name={"eA": "hostA", "eB": "hostB"})
    engine = E.Engine(pool)
    ra = ObjectRef(id="a" * 32, size=1000)
    rb = ObjectRef(id="b" * 32, size=1000)

    class _Client:
        def residency(self, refs):
            return {("a" * 32): ("hostA", "shm"),
                    ("b" * 32): ("hostB", "shm")}

    monkeypatch.setattr(E, "get_client", lambda: _Client())
    # each task reads 1000 bytes from BOTH hosts: a dead tie, rotated
    tied_task = [ra, rb]
    assert engine._locality([tied_task, tied_task, tied_task, tied_task]) \
        == ["eA", "eB", "eA", "eB"]


def test_pick_weighted_skips_draining_host():
    """The heaviest host that still has a DISPATCHABLE member wins: when
    the shm-local host is draining, the runner-up (e.g. the machine with
    the spilled copy) takes the task instead of an arbitrary executor."""
    pool = ExecutorPool([StubExecutor(name="eA"), StubExecutor(name="eB")],
                        hosts_by_name={"eA": "hostA", "eB": "hostB"})
    assert pool.pick_weighted({"hostA": 10.0, "hostB": 1.0}) == "eA"
    assert pool.begin_drain("eA")
    assert pool.pick_weighted({"hostA": 10.0, "hostB": 1.0}) == "eB"
    # nothing dispatchable at any weighted host: no preference
    assert pool.pick_weighted({"hostZ": 5.0}) is None
    assert pool.pick_weighted({}) is None


def test_locality_stream_bucket_sees_tiers(monkeypatch):
    """A streaming reducer's seal-driven ranges weight by residency tier
    too: a big spilled seal can lose to a smaller shm seal elsewhere."""
    pool = ExecutorPool([StubExecutor(name="eA"), StubExecutor(name="eB")],
                        hosts_by_name={"eA": "hostA", "eB": "hostB"})
    engine = E.Engine(pool)
    ra = ObjectRef(id="a" * 32, size=5000)   # spilled on hostA
    rb = ObjectRef(id="b" * 32, size=4000)   # shm on hostB

    class _Client:
        def residency(self, refs):
            return {("a" * 32): ("hostA", "spilled"),
                    ("b" * 32): ("hostB", "shm")}

    monkeypatch.setattr(E, "get_client", lambda: _Client())
    rec = E._StreamStageRec("ss-tier", "repartition", num_maps=2)
    rec.seals[0] = (ra, [(0, 5000, 10)])
    rec.seals[1] = (rb, [(0, 4000, 8)])
    # bucket 0 reads 5000 spilled (-> 2500) + 4000 shm: hostB wins even
    # though hostA holds more raw bytes
    assert engine._locality([[E._StreamBucket(rec, 0)]]) == ["eB"]
    # at full spilled weight the raw byte count would win instead
    monkeypatch.setenv("RDT_LOCALITY_SPILLED_WEIGHT", "1.0")
    assert engine._locality([[E._StreamBucket(rec, 0)]]) == ["eA"]


# ==== remote residency tier scoring (ISSUE 20, ROADMAP 4b) ===================


def _gravity_fixture(monkeypatch, residency):
    """Two-host pool + engine with a stubbed bulk residency RPC."""
    pool = ExecutorPool([StubExecutor(name="eA"), StubExecutor(name="eB")],
                        hosts_by_name={"eA": "hostA", "eB": "hostB"})
    engine = E.Engine(pool)

    class _Client:
        def residency(self, refs):
            return residency

    monkeypatch.setattr(E, "get_client", lambda: _Client())
    return pool, engine


def test_remote_weight_keeps_holder_ranking(monkeypatch):
    """Remote crediting is ranking-NEUTRAL among byte-holders: each host
    scores ``(1-r)*local + r*total`` — monotone in its local bytes — so
    for any r < 1 the shm holder still beats a bigger spilled pile and a
    non-holder never outranks a holder."""
    pool, engine = _gravity_fixture(monkeypatch, {
        ("a" * 32): ("hostA", "shm"),
        ("b" * 32): ("hostB", "spilled")})
    ra = ObjectRef(id="a" * 32, size=1000)
    rb = ObjectRef(id="b" * 32, size=1600)   # spilled at 0.5 -> 800
    for r in ("0.25", "0.9"):
        monkeypatch.setenv("RDT_LOCALITY_REMOTE_WEIGHT", r)
        assert engine._locality([[ra, rb]]) == ["eA"], r
        # sole holder still wins over the credited non-holder
        assert engine._locality([[ra]]) == ["eA"], r


def test_remote_weight_gives_live_nonholder_a_fallback(monkeypatch):
    """The point of the knob: when the gravity host is draining, a LIVE
    non-holder carries a real remote-discounted score, so pick_weighted
    returns a ranked fallback instead of no preference — and remote
    weight 0 restores the holder-only behavior (no fallback)."""
    pool, engine = _gravity_fixture(monkeypatch, {
        ("a" * 32): ("hostA", "shm")})
    ra = ObjectRef(id="a" * 32, size=1000)
    assert pool.begin_drain("eA")
    monkeypatch.setenv("RDT_LOCALITY_REMOTE_WEIGHT", "0.25")
    assert engine._locality([[ra]]) == ["eB"], \
        "live non-holder must become the ranked fallback"
    monkeypatch.setenv("RDT_LOCALITY_REMOTE_WEIGHT", "0")
    assert engine._locality([[ra]]) == [None], \
        "weight 0 must restore holder-only scoring"


def test_remote_weight_one_is_distance_blind(monkeypatch):
    """r=1 credits every live host the task's full bytes: all hosts tie
    and rotate — the distance-blind ceiling of the knob (values above 1
    clamp, so preference can never invert toward non-holders)."""
    pool, engine = _gravity_fixture(monkeypatch, {
        ("a" * 32): ("hostA", "shm")})
    ra = ObjectRef(id="a" * 32, size=1000)
    monkeypatch.setenv("RDT_LOCALITY_REMOTE_WEIGHT", "1.0")
    task = [ra]
    assert engine._locality([task, task, task, task]) \
        == ["eA", "eB", "eA", "eB"]
    # clamp: 5.0 behaves as 1.0, not as an inverted preference
    monkeypatch.setenv("RDT_LOCALITY_REMOTE_WEIGHT", "5.0")
    assert engine._locality([task, task]) == ["eA", "eB"]
