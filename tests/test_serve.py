"""Serving-plane tests (ISSUE 11).

Two layers, mirroring how the plane is built:

- **dispatcher units** — :class:`ServingSession`'s micro-batching, demux,
  routing, hedging, and fault re-route driven against in-process fake
  replica handles (no actors, no jax): fast, deterministic, and able to
  script failure shapes no real schedule can time reliably.
- **integration** — a real 2-executor session: estimator fit → export →
  executor-resident replicas, with the coalesced results asserted
  BIT-identical to the estimator's own ``predict`` (the jitted apply is
  row-independent, so batch composition must not leak into results).

The replica-crash chaos leg lives in tests/test_chaos.py with the other
seeded-injection coverage.
"""

import os
import threading
import time
from concurrent.futures import Future

os.environ.setdefault("KERAS_BACKEND", "jax")

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from raydp_tpu.runtime.rpc import ConnectionLost, RemoteError
from raydp_tpu.serve import ServingError, ServingSession
from raydp_tpu.serve.session import _as_table


# ---------------------------------------------------------------------------
# fake replica handles: duck-typed ActorHandles serving 2*v in-process
# ---------------------------------------------------------------------------

def _decode_payload(payload: bytes) -> pa.Table:
    return pa.ipc.open_stream(pa.py_buffer(payload)).read_all()


class FakeReplicaHandle:
    """Serves ``2 * v`` per row on a thread after ``delay_s()`` seconds;
    ``fail`` scripts an infrastructure failure per call, ``app_fail`` a
    deterministic application error (a remote ValueError)."""

    def __init__(self, name, delay_s=0.0, fail: bool = False,
                 app_fail: bool = False, fail_delay_s: float = 0.01):
        self.name = name
        self.delay_s = delay_s
        self.fail = fail
        self.app_fail = app_fail
        self.fail_delay_s = fail_delay_s
        self.loads = 0
        self.calls = 0
        self._lock = threading.Lock()

    def call(self, method, *args, timeout=None, **kwargs):
        if method == "serve_load":
            with self._lock:
                self.loads += 1
            return {"replica": args[0]}
        if method == "serve_unload":
            return True
        raise AssertionError(f"unexpected call {method}")

    def submit(self, method, *args, **kwargs):
        fut: Future = Future()
        if method == "serve_load":
            with self._lock:
                self.loads += 1
            fut.set_result({"replica": args[0]})
            return fut
        assert method == "serve_predict"
        _rid, payload = args
        with self._lock:
            self.calls += 1
        threading.Thread(target=self._serve, args=(payload, fut),
                         daemon=True).start()
        return fut

    def _serve(self, payload, fut):
        if self.fail:
            time.sleep(self.fail_delay_s)
            fut.set_exception(ConnectionLost(f"{self.name} is scripted down"))
            return
        if self.app_fail:
            time.sleep(self.fail_delay_s)
            fut.set_exception(RemoteError("ValueError", "bad rows", "<tb>"))
            return
        d = self.delay_s() if callable(self.delay_s) else self.delay_s
        if d:
            time.sleep(d)
        table = _decode_payload(payload)
        v = table.column("v").to_numpy(zero_copy_only=False)
        fut.set_result((v * 2.0).astype(np.float32))


def _serving(replicas, monkeypatch, *, max_batch=1000, timeout_ms=40.0,
             hedge=False, hedge_mult=2.0, hedge_min_ms=50.0,
             grace_s=10.0, inflight=2):
    monkeypatch.setenv("RDT_SERVE_MAX_BATCH", str(max_batch))
    monkeypatch.setenv("RDT_SERVE_BATCH_TIMEOUT_MS", str(timeout_ms))
    monkeypatch.setenv("RDT_SERVE_HEDGE", "1" if hedge else "0")
    monkeypatch.setenv("RDT_SERVE_HEDGE_QUANTILE", "0.5")
    monkeypatch.setenv("RDT_SERVE_HEDGE_MULTIPLIER", str(hedge_mult))
    monkeypatch.setenv("RDT_SERVE_HEDGE_MIN_MS", str(hedge_min_ms))
    monkeypatch.setenv("RDT_SERVE_REROUTE_GRACE_S", str(grace_s))
    monkeypatch.setenv("RDT_SERVE_MAX_INFLIGHT", str(inflight))
    return ServingSession("/nonexistent/bundle", executors=replicas,
                          name="t")


def _rows(*vals):
    return {"v": np.asarray(vals, np.float64)}


def test_as_table_accepts_frames_tables_dicts():
    t = _as_table(pa.table({"v": [1.0]}))
    assert t.num_rows == 1
    t = _as_table(pd.DataFrame({"v": [1.0, 2.0]}))
    assert t.num_rows == 2
    t = _as_table({"v": np.array([3.0])})
    assert t.num_rows == 1
    with pytest.raises(TypeError):
        _as_table([1, 2, 3])


def test_coalescing_batches_and_demuxes(monkeypatch):
    """A burst of single-row requests coalesces into far fewer dispatches,
    and every caller gets exactly its own row back."""
    fakes = [FakeReplicaHandle("a", delay_s=0.02),
             FakeReplicaHandle("b", delay_s=0.02)]
    srv = _serving(fakes, monkeypatch, timeout_ms=40.0)
    try:
        futs = [srv.predict_async(_rows(float(i))) for i in range(64)]
        got = [f.result(timeout=30.0) for f in futs]
        for i, g in enumerate(got):
            assert g.shape == (1,)
            assert g[0] == np.float32(2.0 * i)
        rep = srv.serving_report()
        assert rep["requests"] == 64
        assert rep["batches"] < 64          # coalescing actually happened
        assert rep["rows"] == 64
        assert rep["mean_batch_occupancy"] > 1.0
        assert rep["failed"] == 0
    finally:
        srv.close()


def test_timeout_flushes_a_lone_request(monkeypatch):
    """A single request never waits for a batch to fill: the latency budget
    flushes it."""
    srv = _serving([FakeReplicaHandle("a")], monkeypatch,
                   max_batch=100000, timeout_ms=30.0)
    try:
        t0 = time.monotonic()
        out = srv.predict(_rows(21.0), timeout=30.0)
        wall = time.monotonic() - t0
        assert out[0] == np.float32(42.0)
        assert wall < 5.0
        rep = srv.serving_report()
        assert rep["batches"] == 1 and rep["max_batch_occupancy"] == 1
    finally:
        srv.close()


def test_full_batch_dispatches_before_timeout(monkeypatch):
    """Hitting the row cap flushes immediately — the budget is a ceiling,
    not a tax on full batches."""
    fake = FakeReplicaHandle("a")
    srv = _serving([fake], monkeypatch, max_batch=8, timeout_ms=60_000.0)
    try:
        futs = [srv.predict_async(_rows(float(i))) for i in range(8)]
        t0 = time.monotonic()
        for f in futs:
            f.result(timeout=30.0)
        assert time.monotonic() - t0 < 10.0  # nowhere near the 60s budget
    finally:
        srv.close()


def test_oversized_request_is_its_own_batch(monkeypatch):
    """A request above RDT_SERVE_MAX_BATCH dispatches alone, un-split."""
    srv = _serving([FakeReplicaHandle("a")], monkeypatch, max_batch=4,
                   timeout_ms=10.0)
    try:
        vals = np.arange(10, dtype=np.float64)
        out = srv.predict({"v": vals}, timeout=30.0)
        assert np.array_equal(out, (vals * 2).astype(np.float32))
        rep = srv.serving_report()
        assert rep["max_batch_occupancy"] == 10
    finally:
        srv.close()


def test_demux_ordering_under_interleaved_threads(monkeypatch):
    """Requests issued from many threads each get their own rows, in their
    own order, regardless of how the dispatcher packed them."""
    fakes = [FakeReplicaHandle("a", delay_s=0.01),
             FakeReplicaHandle("b", delay_s=0.01)]
    srv = _serving(fakes, monkeypatch, timeout_ms=20.0)
    errors = []

    def client(base):
        try:
            vals = np.array([base, base + 0.25, base + 0.5])
            out = srv.predict({"v": vals}, timeout=30.0)
            assert np.array_equal(out, (vals * 2).astype(np.float32))
        except Exception as e:  # noqa: BLE001 - surfaced below
            errors.append(e)

    try:
        threads = [threading.Thread(target=client, args=(float(i),))
                   for i in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30.0)
        assert not errors
        rep = srv.serving_report()
        assert rep["requests"] == 16 and rep["rows"] == 48
    finally:
        srv.close()


def test_routing_spreads_over_replicas(monkeypatch):
    fakes = [FakeReplicaHandle("a"), FakeReplicaHandle("b")]
    srv = _serving(fakes, monkeypatch, max_batch=1, timeout_ms=0.0)
    try:
        for i in range(10):
            srv.predict(_rows(float(i)), timeout=30.0)
        rep = srv.serving_report()
        per = {r["replica"]: r["batches"] for r in rep["replicas"]}
        assert all(n >= 1 for n in per.values()), per
    finally:
        srv.close()


def test_hedging_wins_and_accounts(monkeypatch):
    """A replica that turns slow after warmup gets hedged: the fast sibling
    answers, the request never waits out the straggler, and the counters
    record the race both ways."""
    slow_after = {"n": 0}

    def a_delay():
        slow_after["n"] += 1
        return 0.0 if slow_after["n"] <= 8 else 1.5

    fakes = [FakeReplicaHandle("a", delay_s=a_delay),
             FakeReplicaHandle("b", delay_s=0.0)]
    srv = _serving(fakes, monkeypatch, max_batch=1, timeout_ms=0.0,
                   hedge=True, hedge_mult=2.0, hedge_min_ms=50.0)
    try:
        # warmup: sequential requests alternate replicas, recording >= 8
        # fast batch latencies (the hedge-eligibility floor)
        for i in range(16):
            srv.predict(_rows(float(i)), timeout=30.0)
        # now replica a is a straggler: every request it receives should
        # hedge onto b and complete far below a's 1.5s delay
        t0 = time.monotonic()
        futs = [srv.predict_async(_rows(100.0 + i)) for i in range(4)]
        got = [f.result(timeout=30.0) for f in futs]
        wall = time.monotonic() - t0
        for i, g in enumerate(got):
            assert g[0] == np.float32(2.0 * (100.0 + i))
        assert wall < 1.4, f"hedging did not cut the straggler tail: {wall}"
        rep = srv.serving_report()
        assert rep["hedged"] >= 1
        assert rep["hedge_won"] >= 1
        assert rep["failed"] == 0
        # the losers land ~1.5s later and are discarded+counted
        deadline = time.time() + 5.0
        while time.time() < deadline:
            rep = srv.serving_report()
            if rep["hedge_lost"] >= 1:
                break
            time.sleep(0.1)
        assert rep["hedge_lost"] >= 1
    finally:
        srv.close()


def test_failed_replica_reroutes_and_reloads(monkeypatch):
    """Every request that lands on the scripted-down replica re-routes to
    the live one; the dead replica's background reload is attempted."""
    down = FakeReplicaHandle("a", fail=True)
    up = FakeReplicaHandle("b")
    srv = _serving([down, up], monkeypatch, max_batch=1, timeout_ms=0.0)
    try:
        for i in range(6):
            out = srv.predict(_rows(float(i)), timeout=30.0)
            assert out[0] == np.float32(2.0 * i)
        rep = srv.serving_report()
        assert rep["failed"] == 0
        assert rep["rerouted"] >= 1          # some requests hit the down one
        assert down.loads >= 2               # initial load + reload attempt
    finally:
        srv.close()


def test_app_error_fails_fast_without_reroute(monkeypatch):
    """A deterministic application error (a remote ValueError) must fail
    the request immediately — replaying it on the sibling replica would
    replay the error, and burning the 30s re-route grace on it is the
    failure mode doc/serving.md's table rules out."""
    srv = _serving([FakeReplicaHandle("a", app_fail=True),
                    FakeReplicaHandle("b", app_fail=True)],
                   monkeypatch, max_batch=1, timeout_ms=0.0, grace_s=30.0)
    try:
        t0 = time.monotonic()
        with pytest.raises(ServingError) as ei:
            srv.predict(_rows(1.0), timeout=30.0)
        assert time.monotonic() - t0 < 5.0
        assert "ValueError" in str(ei.value)
        rep = srv.serving_report()
        assert rep["rerouted"] == 0       # never bounced between replicas
    finally:
        srv.close()


def test_reload_rebinds_replica_off_retired_executor(monkeypatch):
    """Satellite (ISSUE 13): the background reload used to probe a FIXED
    executor identity until the re-route grace expired. With the owning
    session's live-member view available, a replica whose executor was
    retired from the pool re-homes onto a surviving member and reloads
    there — requests keep flowing the whole time."""
    from types import SimpleNamespace

    class RetireableHandle(FakeReplicaHandle):
        def __init__(self, name):
            super().__init__(name)
            self.dead = False

        def call(self, method, *args, timeout=None, **kwargs):
            if self.dead:
                raise ConnectionLost(f"{self.name} was retired")
            return super().call(method, *args, timeout=timeout, **kwargs)

        def submit(self, method, *args, **kwargs):
            if self.dead:
                raise ConnectionLost(f"{self.name} was retired")
            return super().submit(method, *args, **kwargs)

    r0 = RetireableHandle("ex0")
    r1 = FakeReplicaHandle("ex1")
    r2 = FakeReplicaHandle("ex2")
    monkeypatch.setenv("RDT_SERVE_MAX_BATCH", "1000")
    monkeypatch.setenv("RDT_SERVE_BATCH_TIMEOUT_MS", "5")
    monkeypatch.setenv("RDT_SERVE_HEDGE", "0")
    monkeypatch.setenv("RDT_SERVE_REROUTE_GRACE_S", "20")
    # the session's live-member view: ex0 already retired, ex2 a survivor
    # that never hosted a replica
    fake_session = SimpleNamespace(executors=[r1, r2])
    srv = ServingSession("/nonexistent/bundle", session=fake_session,
                         executors=[r0, r1], name="t")
    try:
        r0.dead = True  # the retirement lands after construction
        # first dispatch routes to t-r0 (round-robin start), fails, and
        # re-routes; the reload must re-home t-r0 onto ex2 (least loaded
        # live member), not keep dialing the corpse
        out = srv.predict(_rows(1.0, 2.0), timeout=30.0)
        np.testing.assert_allclose(out, [2.0, 4.0])
        deadline = time.time() + 20
        rep0 = None
        while time.time() < deadline:
            rep0 = next(r for r in srv.serving_report()["replicas"]
                        if r["replica"] == "t-r0")
            if rep0["ready"] and rep0["executor"] == "ex2":
                break
            time.sleep(0.1)
        assert rep0 and rep0["executor"] == "ex2", rep0
        assert rep0["ready"], rep0
        assert r2.loads >= 1, "survivor never loaded the re-homed replica"
        # and the re-homed replica serves again
        out2 = srv.predict(_rows(3.0), timeout=30.0)
        np.testing.assert_allclose(out2, [6.0])
        assert srv.serving_report()["failed"] == 0
    finally:
        srv.close()


def test_mixed_schemas_coalesce_separately(monkeypatch):
    """Requests with different schemas in one batching window dispatch as
    separate batches — a mixed concat would fail and punish well-formed
    requests (and, pre-fix, killed the dispatcher thread outright)."""
    srv = _serving([FakeReplicaHandle("a")], monkeypatch, timeout_ms=40.0)
    try:
        f1 = srv.predict_async({"v": np.array([1.0]),
                                "extra": np.array([9.0])})
        f2 = srv.predict_async(_rows(2.0))
        assert f2.result(timeout=30.0)[0] == np.float32(4.0)
        assert f1.result(timeout=30.0)[0] == np.float32(2.0)
        # the session survives and keeps serving
        assert srv.predict(_rows(3.0), timeout=30.0)[0] == np.float32(6.0)
    finally:
        srv.close()


def test_every_replica_down_fails_within_grace(monkeypatch):
    srv = _serving([FakeReplicaHandle("a", fail=True),
                    FakeReplicaHandle("b", fail=True)],
                   monkeypatch, max_batch=1, timeout_ms=0.0, grace_s=1.0)
    try:
        with pytest.raises(ServingError):
            srv.predict(_rows(1.0), timeout=30.0)
        rep = srv.serving_report()
        assert rep["failed"] >= 1
    finally:
        srv.close()


def test_report_columns(monkeypatch):
    srv = _serving([FakeReplicaHandle("a")], monkeypatch)
    try:
        srv.predict(_rows(1.0), timeout=30.0)
        rep = srv.serving_report()
        for col in ("requests", "batches", "rows", "p50_ms", "p99_ms",
                    "mean_batch_occupancy", "max_batch_occupancy",
                    "queue_depth", "queue_depth_peak", "hedged",
                    "hedge_won", "hedge_lost", "rerouted", "failed",
                    "replicas"):
            assert col in rep, col
        assert rep["p99_ms"] >= rep["p50_ms"] >= 0.0
        r0 = rep["replicas"][0]
        for col in ("replica", "executor", "ready", "requests", "batches",
                    "rows", "hedges", "inflight", "inflight_peak",
                    "reloads"):
            assert col in r0, col
    finally:
        srv.close()


def test_closed_session_refuses_and_empty_request_shortcuts(monkeypatch):
    srv = _serving([FakeReplicaHandle("a")], monkeypatch)
    out = srv.predict(_rows(), timeout=5.0)   # 0 rows: answered inline
    assert out.shape == (0,)
    srv.close()
    with pytest.raises(ServingError):
        srv.predict_async(_rows(1.0))
    # post-close report still answers (snapshot, no dispatcher)
    assert "requests" in srv.serving_report()


# ---------------------------------------------------------------------------
# integration: real executors, real estimator, real bundles
# ---------------------------------------------------------------------------

def _linear_data(n=256):
    rng = np.random.RandomState(3)
    x = rng.random_sample((n, 2))
    y = x @ np.array([2.0, -3.0]) + 1.0
    return pd.DataFrame({"x1": x[:, 0], "x2": x[:, 1], "y": y})


@pytest.fixture(scope="module")
def served_model(tmp_path_factory):
    """One 2-executor session + one trained/exported flax estimator shared
    by the integration tests (executor-side jax import paid once)."""
    import optax

    import raydp_tpu
    from raydp_tpu.models import MLP
    from raydp_tpu.train import FlaxEstimator

    s = raydp_tpu.init("serve_it", num_executors=2, executor_cores=1,
                       executor_memory="512MB")
    try:
        pdf = _linear_data()
        df = s.createDataFrame(pdf, num_partitions=2)
        est = FlaxEstimator(
            model=MLP(features=(8,), use_batch_norm=False),
            optimizer=optax.adam(1e-2), loss="mse",
            feature_columns=["x1", "x2"], label_column="y",
            batch_size=64, num_epochs=1)
        est.fit_on_frame(df)
        export_dir = str(tmp_path_factory.mktemp("servable") / "flax")
        est.export_serving(export_dir)
        yield s, est, export_dir, pdf
    finally:
        raydp_tpu.stop()


def test_flax_servable_roundtrip_matches_predict(served_model):
    """load_servable() in-process reproduces estimator.predict bitwise on
    the same rows."""
    from raydp_tpu.data.dataset import from_frame
    from raydp_tpu.serve import load_servable

    s, est, export_dir, pdf = served_model
    sv = load_servable(export_dir)
    table = pa.table({"x1": pdf["x1"].values, "x2": pdf["x2"].values})
    got = sv.predict_table(table)
    df = s.createDataFrame(pdf, num_partitions=2)
    ref = est.predict(from_frame(df.select("x1", "x2")))
    assert np.array_equal(got, ref)


def test_serving_session_row_identical_to_predict(served_model,
                                                  monkeypatch):
    """The acceptance matrix's core equality: concurrent coalesced serving
    returns, per request, exactly the rows a driver-side predict computes —
    coalescing must be invisible in the bits."""
    from raydp_tpu.data.dataset import from_frame
    from raydp_tpu.serve import ServingSession

    s, est, export_dir, pdf = served_model
    df = s.createDataFrame(pdf, num_partitions=2)
    ref = est.predict(from_frame(df.select("x1", "x2")))

    monkeypatch.setenv("RDT_SERVE_BATCH_TIMEOUT_MS", "20")
    monkeypatch.setenv("RDT_SERVE_HEDGE", "0")
    srv = ServingSession(export_dir, session=s, name="it")
    try:
        n = len(pdf)
        futs = [srv.predict_async(
            {"x1": pdf["x1"].values[i:i + 4], "x2": pdf["x2"].values[i:i + 4]})
            for i in range(0, n, 4)]
        got = np.concatenate([f.result(timeout=120.0) for f in futs])
        assert np.array_equal(got, ref)
        rep = srv.serving_report()
        assert rep["requests"] == n // 4
        assert rep["batches"] < rep["requests"]   # coalescing on real RPCs
        assert rep["failed"] == 0
        assert sum(r["batches"] for r in rep["replicas"]) == rep["batches"]
    finally:
        srv.close()


def test_serve_stats_and_unload(served_model, monkeypatch):
    from raydp_tpu.serve import ServingSession

    s, _est, export_dir, pdf = served_model
    monkeypatch.setenv("RDT_SERVE_HEDGE", "0")
    srv = ServingSession(export_dir, session=s, name="stats")
    try:
        srv.predict({"x1": pdf["x1"].values[:8],
                     "x2": pdf["x2"].values[:8]}, timeout=60.0)
        stats = s.executors[0].call("serve_stats")
        mine = [r for r in stats["replicas"]
                if r["replica"].startswith("stats-")]
        assert mine and mine[0]["model_nbytes"] > 0
    finally:
        srv.close()
    # after close(unload=True) the replicas are gone from the registry
    stats = s.executors[0].call("serve_stats")
    assert not any(r["replica"].startswith("stats-")
                   for r in stats["replicas"])


def test_replica_not_loaded_is_typed(served_model):
    s, _est, _export_dir, _pdf = served_model
    with pytest.raises(RemoteError) as ei:
        s.executors[0].call("serve_predict", "no-such-replica", b"")
    assert ei.value.exc_type == "ReplicaNotLoaded"


def test_keras_servable_roundtrip(served_model, tmp_path):
    """Keras export → load_servable reproduces KerasEstimator.predict
    bitwise (architecture from the pickled model, weights from the
    checkpoint). Rides the shared session — init() is a singleton."""
    keras = pytest.importorskip("keras")
    from raydp_tpu.data.dataset import from_frame
    from raydp_tpu.serve import load_servable
    from raydp_tpu.train import KerasEstimator

    s, _est, _export_dir, pdf = served_model
    df = s.createDataFrame(pdf.iloc[:128], num_partitions=1)
    model = keras.Sequential([
        keras.layers.Input((2,)),
        keras.layers.Dense(4, activation="relu"),
        keras.layers.Dense(1),
    ])
    model.compile(optimizer="adam", loss="mse")
    est = KerasEstimator(model=model, feature_columns=["x1", "x2"],
                         label_column="y", batch_size=64, num_epochs=1)
    est.fit_on_frame(df)
    export_dir = str(tmp_path / "keras-bundle")
    est.export_serving(export_dir)
    sv = load_servable(export_dir)
    got = sv.predict_table(
        pa.table({"x1": pdf["x1"].values[:128], "x2": pdf["x2"].values[:128]}))
    ref = est.predict(from_frame(df.select("x1", "x2")))
    assert np.array_equal(got, ref)


def test_export_requires_fit(tmp_path):
    import optax

    from raydp_tpu.models import MLP
    from raydp_tpu.train import FlaxEstimator
    from raydp_tpu.train.gbdt_estimator import GBDTEstimator

    est = FlaxEstimator(model=MLP(features=(4,), use_batch_norm=False),
                        optimizer=optax.adam(1e-2),
                        feature_columns=["a"], label_column="b")
    with pytest.raises(RuntimeError):
        est.export_serving(str(tmp_path / "x"))
    with pytest.raises(NotImplementedError):
        GBDTEstimator(feature_columns=["a"],
                      label_column="b").export_serving(str(tmp_path / "y"))


# ---------------------------------------------------------------------------
# overload shedding (ISSUE 14): typed rejections, dispatcher stays alive
# ---------------------------------------------------------------------------

def test_overload_sheds_typed_and_dispatcher_survives(monkeypatch):
    """Past RDT_SERVE_MAX_QUEUE outstanding requests predict_async fails
    fast with the typed retriable ServingOverloaded; accepted requests
    keep serving byte-correct results, the report shows failed == shed,
    and — the retriable contract — the session accepts again once the
    queue drains."""
    from raydp_tpu.serve import ServingOverloaded

    monkeypatch.setenv("RDT_SERVE_MAX_QUEUE", "4")
    slow = FakeReplicaHandle("a", delay_s=0.25)
    srv = _serving([slow], monkeypatch, max_batch=1, timeout_ms=0.0,
                   inflight=1)
    try:
        futs, sheds = [], 0
        for i in range(12):
            try:
                futs.append((i, srv.predict_async(_rows(float(i)))))
            except ServingOverloaded as e:
                assert isinstance(e, ServingError)  # subclass: one catch
                sheds += 1
        assert sheds >= 1, "queue bound never shed"
        assert len(futs) >= 4  # the bound's worth was accepted
        for i, f in futs:
            got = f.result(timeout=30.0)
            assert got[0] == np.float32(2.0 * i)  # accepted = byte-correct
        rep = srv.serving_report()
        assert rep["shed"] == sheds
        assert rep["failed"] == rep["shed"], rep  # failed == shed ONLY
        assert rep["outstanding"] == 0
        assert rep["max_queue"] == 4
        # retriable: the drained session accepts and serves again
        assert srv.predict(_rows(99.0), timeout=30.0)[0] \
            == np.float32(198.0)
    finally:
        srv.close()


def test_overload_shed_disabled_by_zero(monkeypatch):
    monkeypatch.setenv("RDT_SERVE_MAX_QUEUE", "0")
    srv = _serving([FakeReplicaHandle("a", delay_s=0.05)], monkeypatch,
                   max_batch=1, timeout_ms=0.0, inflight=1)
    try:
        futs = [srv.predict_async(_rows(float(i))) for i in range(32)]
        for i, f in enumerate(futs):
            assert f.result(timeout=30.0)[0] == np.float32(2.0 * i)
        rep = srv.serving_report()
        assert rep["shed"] == 0 and rep["failed"] == 0
    finally:
        srv.close()


def test_hedging_suppressed_while_shedding(monkeypatch):
    """A saturated session must not hedge: the duplicate dispatch would
    amplify the very overload being shed. The same straggler that hedges
    under an uncontended queue rides out its full delay when the
    outstanding queue sits at the bound."""
    for max_queue, expect_hedge in (("100", True), ("1", False)):
        monkeypatch.setenv("RDT_SERVE_MAX_QUEUE", max_queue)
        slow_after = {"n": 0}

        def a_delay():
            slow_after["n"] += 1
            return 0.0 if slow_after["n"] <= 8 else 1.0

        fakes = [FakeReplicaHandle("a", delay_s=a_delay),
                 FakeReplicaHandle("b", delay_s=0.0)]
        srv = _serving(fakes, monkeypatch, max_batch=1, timeout_ms=0.0,
                       hedge=True, hedge_mult=2.0, hedge_min_ms=50.0)
        try:
            for i in range(16):  # warmup: record the fast latency floor
                srv.predict(_rows(float(i)), timeout=30.0)
            # one straggler dispatch; with max_queue=1 the lone
            # outstanding request saturates the session
            t0 = time.monotonic()
            while True:  # land a request on the (now slow) replica a
                got = srv.predict(_rows(123.0), timeout=30.0)
                if slow_after["n"] > 9:
                    break
            wall = time.monotonic() - t0
            assert got[0] == np.float32(246.0)
            rep = srv.serving_report()
            if expect_hedge:
                assert rep["hedged"] >= 1, (max_queue, rep)
            else:
                assert rep["hedged"] == 0, (max_queue, rep)
                assert wall >= 0.9, "suppressed hedge still cut the tail?"
        finally:
            srv.close()


# ---------------------------------------------------------------------------
# hot swap (ISSUE 15): versioned servables under live traffic
# ---------------------------------------------------------------------------

class VersionedFakeReplica(FakeReplicaHandle):
    """A fake whose answer depends on the LOADED bundle: a replica id
    loaded from ``.../vN`` answers ``(N + 1) * v`` — so every response
    names the exact servable version that computed it."""

    def __init__(self, name, delay_s=0.0):
        super().__init__(name, delay_s=delay_s)
        self.dirs: dict = {}        # rid -> export dir
        self.unloaded: list = []

    def call(self, method, *args, timeout=None, **kwargs):
        if method == "serve_unload":
            with self._lock:
                self.dirs.pop(args[0], None)
                self.unloaded.append(args[0])
            return True
        return super().call(method, *args, timeout=timeout, **kwargs)

    def submit(self, method, *args, **kwargs):
        if method == "serve_load":
            rid, export_dir = args
            with self._lock:
                self.dirs[rid] = export_dir
                self.loads += 1
            fut = Future()
            fut.set_result({"replica": rid})
            return fut
        assert method == "serve_predict"
        rid, payload = args
        with self._lock:
            self.calls += 1
            mult = int(self.dirs[rid].rsplit("v", 1)[1]) + 1
        fut = Future()
        threading.Thread(target=self._serve_versioned,
                         args=(payload, fut, mult), daemon=True).start()
        return fut

    def _serve_versioned(self, payload, fut, mult):
        d = self.delay_s() if callable(self.delay_s) else self.delay_s
        if d:
            time.sleep(d)
        table = _decode_payload(payload)
        v = table.column("v").to_numpy(zero_copy_only=False)
        fut.set_result((v * float(mult)).astype(np.float32))


def test_hot_swap_shifts_traffic_and_reports_active_version(monkeypatch):
    monkeypatch.setenv("RDT_SERVE_BATCH_TIMEOUT_MS", "5")
    monkeypatch.setenv("RDT_SERVE_HEDGE", "0")
    monkeypatch.setenv("RDT_SERVE_SWAP_DRAIN_S", "5")
    reps = [VersionedFakeReplica("a"), VersionedFakeReplica("b")]
    srv = ServingSession("/bundles/v1", executors=reps, name="hs")
    try:
        assert np.array_equal(srv.predict(_rows(1.0, 2.0)), [2.0, 4.0])
        rep = srv.serving_report()
        assert rep["servable"] == {"version": 1,
                                   "export_dir": "/bundles/v1",
                                   "tag": None}
        info = srv.hot_swap("/bundles/v2", tag="epoch-9")
        assert info["version"] == 2
        assert info["replicas"] == ["hs-v2-r0", "hs-v2-r1"]
        # every post-swap dispatch answers from v2
        assert np.array_equal(srv.predict(_rows(1.0, 2.0)), [3.0, 6.0])
        rep = srv.serving_report()
        assert rep["servable"] == {"version": 2,
                                   "export_dir": "/bundles/v2",
                                   "tag": "epoch-9"}
        assert rep["hot_swaps"] == 1
        # the old version retires (drained: nothing in flight on it)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline \
                and not all(h.unloaded for h in reps):
            time.sleep(0.02)
        assert [u for h in reps for u in h.unloaded] \
            == ["hs-r0", "hs-r1"]
    finally:
        srv.close()


def test_hot_swap_racing_predict_burst_zero_dropped(monkeypatch):
    """The ISSUE 15 race leg at unit precision: a predict burst straddles
    two hot-swaps while the outgoing version still holds in-flight work
    (a scripted apply delay) — zero dropped requests, and every response
    is the output of exactly ONE servable version (2v, 3v or 4v — never a
    mix within one request, never a value from no version)."""
    monkeypatch.setenv("RDT_SERVE_BATCH_TIMEOUT_MS", "2")
    monkeypatch.setenv("RDT_SERVE_HEDGE", "0")
    monkeypatch.setenv("RDT_SERVE_SWAP_DRAIN_S", "3")
    reps = [VersionedFakeReplica("a", delay_s=0.01),
            VersionedFakeReplica("b", delay_s=0.01)]
    srv = ServingSession("/bundles/v1", executors=reps, name="race")
    try:
        stop = threading.Event()
        futs, errors = [], []

        def fire():
            i = 0
            while not stop.is_set():
                try:
                    futs.append((float(i), srv.predict_async(
                        _rows(float(i)))))
                except Exception as e:  # noqa: BLE001 - counted
                    errors.append(repr(e))
                i += 1
                time.sleep(0.001)

        t = threading.Thread(target=fire)
        t.start()
        time.sleep(0.05)
        srv.hot_swap("/bundles/v2", tag="epoch-2")
        time.sleep(0.05)
        srv.hot_swap("/bundles/v3", tag="epoch-4")
        time.sleep(0.05)
        stop.set()
        t.join(timeout=30)
        assert not errors, errors
        assert len(futs) > 20
        versions = set()
        for v, f in futs:
            got = f.result(timeout=30.0)
            assert got.shape == (1,)
            if v == 0.0:
                continue  # 0 is version-blind
            mult = got[0] / v
            # exactly one version answered: the multiplier is one of the
            # three loaded servables', bit-exact
            assert mult in (2.0, 3.0, 4.0), (v, got)
            versions.add(mult)
        assert len(versions) >= 2, "burst never straddled a swap"
        rep = srv.serving_report()
        assert rep["hot_swaps"] == 2
        assert rep["failed"] == 0 and rep["shed"] == 0
        assert rep["servable"]["version"] == 3
        assert rep["servable"]["tag"] == "epoch-4"
    finally:
        srv.close()


def test_hot_swap_drain_waits_for_inflight_then_unloads(monkeypatch):
    """Retirement semantics: the outgoing version's in-flight dispatch
    completes (no drop), and its replicas unload only after the drain."""
    monkeypatch.setenv("RDT_SERVE_BATCH_TIMEOUT_MS", "2")
    monkeypatch.setenv("RDT_SERVE_HEDGE", "0")
    monkeypatch.setenv("RDT_SERVE_SWAP_DRAIN_S", "10")
    slow = VersionedFakeReplica("slow", delay_s=0.3)
    srv = ServingSession("/bundles/v1", executors=[slow], name="drain")
    try:
        f = srv.predict_async(_rows(5.0))   # in flight on v1, 300ms apply
        time.sleep(0.05)
        srv.hot_swap("/bundles/v2")
        assert not slow.unloaded            # v1 still busy: not retired yet
        assert np.array_equal(f.result(timeout=30.0), [10.0])  # v1 answered
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and not slow.unloaded:
            time.sleep(0.02)
        assert slow.unloaded == ["drain-r0"]
        assert np.array_equal(srv.predict(_rows(5.0)), [15.0])  # v2 now
    finally:
        srv.close()
