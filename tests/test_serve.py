"""Serving-plane tests (ISSUE 11).

Two layers, mirroring how the plane is built:

- **dispatcher units** — :class:`ServingSession`'s micro-batching, demux,
  routing, hedging, and fault re-route driven against in-process fake
  replica handles (no actors, no jax): fast, deterministic, and able to
  script failure shapes no real schedule can time reliably.
- **integration** — a real 2-executor session: estimator fit → export →
  executor-resident replicas, with the coalesced results asserted
  BIT-identical to the estimator's own ``predict`` (the jitted apply is
  row-independent, so batch composition must not leak into results).

The replica-crash chaos leg lives in tests/test_chaos.py with the other
seeded-injection coverage.
"""

import os
import threading
import time
from concurrent.futures import Future

os.environ.setdefault("KERAS_BACKEND", "jax")

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from raydp_tpu.runtime.rpc import ConnectionLost, RemoteError
from raydp_tpu.serve import ServingError, ServingSession
from raydp_tpu.serve.session import _as_table


# ---------------------------------------------------------------------------
# fake replica handles: duck-typed ActorHandles serving 2*v in-process
# ---------------------------------------------------------------------------

def _decode_payload(payload: bytes) -> pa.Table:
    return pa.ipc.open_stream(pa.py_buffer(payload)).read_all()


class FakeReplicaHandle:
    """Serves ``2 * v`` per row on a thread after ``delay_s()`` seconds;
    ``fail`` scripts an infrastructure failure per call, ``app_fail`` a
    deterministic application error (a remote ValueError)."""

    def __init__(self, name, delay_s=0.0, fail: bool = False,
                 app_fail: bool = False, fail_delay_s: float = 0.01):
        self.name = name
        self.delay_s = delay_s
        self.fail = fail
        self.app_fail = app_fail
        self.fail_delay_s = fail_delay_s
        self.loads = 0
        self.calls = 0
        self._lock = threading.Lock()

    def call(self, method, *args, timeout=None, **kwargs):
        if method == "serve_load":
            with self._lock:
                self.loads += 1
            return {"replica": args[0]}
        if method == "serve_unload":
            return True
        raise AssertionError(f"unexpected call {method}")

    def submit(self, method, *args, **kwargs):
        fut: Future = Future()
        if method == "serve_load":
            with self._lock:
                self.loads += 1
            fut.set_result({"replica": args[0]})
            return fut
        assert method == "serve_predict"
        _rid, payload = args
        with self._lock:
            self.calls += 1
        threading.Thread(target=self._serve, args=(payload, fut),
                         daemon=True).start()
        return fut

    def _serve(self, payload, fut):
        if self.fail:
            time.sleep(self.fail_delay_s)
            fut.set_exception(ConnectionLost(f"{self.name} is scripted down"))
            return
        if self.app_fail:
            time.sleep(self.fail_delay_s)
            fut.set_exception(RemoteError("ValueError", "bad rows", "<tb>"))
            return
        d = self.delay_s() if callable(self.delay_s) else self.delay_s
        if d:
            time.sleep(d)
        table = _decode_payload(payload)
        v = table.column("v").to_numpy(zero_copy_only=False)
        fut.set_result((v * 2.0).astype(np.float32))


def _serving(replicas, monkeypatch, *, max_batch=1000, timeout_ms=40.0,
             hedge=False, hedge_mult=2.0, hedge_min_ms=50.0,
             grace_s=10.0, inflight=2):
    monkeypatch.setenv("RDT_SERVE_MAX_BATCH", str(max_batch))
    monkeypatch.setenv("RDT_SERVE_BATCH_TIMEOUT_MS", str(timeout_ms))
    monkeypatch.setenv("RDT_SERVE_HEDGE", "1" if hedge else "0")
    monkeypatch.setenv("RDT_SERVE_HEDGE_QUANTILE", "0.5")
    monkeypatch.setenv("RDT_SERVE_HEDGE_MULTIPLIER", str(hedge_mult))
    monkeypatch.setenv("RDT_SERVE_HEDGE_MIN_MS", str(hedge_min_ms))
    monkeypatch.setenv("RDT_SERVE_REROUTE_GRACE_S", str(grace_s))
    monkeypatch.setenv("RDT_SERVE_MAX_INFLIGHT", str(inflight))
    return ServingSession("/nonexistent/bundle", executors=replicas,
                          name="t")


def _rows(*vals):
    return {"v": np.asarray(vals, np.float64)}


def test_as_table_accepts_frames_tables_dicts():
    t = _as_table(pa.table({"v": [1.0]}))
    assert t.num_rows == 1
    t = _as_table(pd.DataFrame({"v": [1.0, 2.0]}))
    assert t.num_rows == 2
    t = _as_table({"v": np.array([3.0])})
    assert t.num_rows == 1
    with pytest.raises(TypeError):
        _as_table([1, 2, 3])


def test_coalescing_batches_and_demuxes(monkeypatch):
    """A burst of single-row requests coalesces into far fewer dispatches,
    and every caller gets exactly its own row back."""
    fakes = [FakeReplicaHandle("a", delay_s=0.02),
             FakeReplicaHandle("b", delay_s=0.02)]
    srv = _serving(fakes, monkeypatch, timeout_ms=40.0)
    try:
        futs = [srv.predict_async(_rows(float(i))) for i in range(64)]
        got = [f.result(timeout=30.0) for f in futs]
        for i, g in enumerate(got):
            assert g.shape == (1,)
            assert g[0] == np.float32(2.0 * i)
        rep = srv.serving_report()
        assert rep["requests"] == 64
        assert rep["batches"] < 64          # coalescing actually happened
        assert rep["rows"] == 64
        assert rep["mean_batch_occupancy"] > 1.0
        assert rep["failed"] == 0
    finally:
        srv.close()


def test_timeout_flushes_a_lone_request(monkeypatch):
    """A single request never waits for a batch to fill: the latency budget
    flushes it."""
    srv = _serving([FakeReplicaHandle("a")], monkeypatch,
                   max_batch=100000, timeout_ms=30.0)
    try:
        t0 = time.monotonic()
        out = srv.predict(_rows(21.0), timeout=30.0)
        wall = time.monotonic() - t0
        assert out[0] == np.float32(42.0)
        assert wall < 5.0
        rep = srv.serving_report()
        assert rep["batches"] == 1 and rep["max_batch_occupancy"] == 1
    finally:
        srv.close()


def test_full_batch_dispatches_before_timeout(monkeypatch):
    """Hitting the row cap flushes immediately — the budget is a ceiling,
    not a tax on full batches."""
    fake = FakeReplicaHandle("a")
    srv = _serving([fake], monkeypatch, max_batch=8, timeout_ms=60_000.0)
    try:
        futs = [srv.predict_async(_rows(float(i))) for i in range(8)]
        t0 = time.monotonic()
        for f in futs:
            f.result(timeout=30.0)
        assert time.monotonic() - t0 < 10.0  # nowhere near the 60s budget
    finally:
        srv.close()


def test_oversized_request_is_its_own_batch(monkeypatch):
    """A request above RDT_SERVE_MAX_BATCH dispatches alone, un-split."""
    srv = _serving([FakeReplicaHandle("a")], monkeypatch, max_batch=4,
                   timeout_ms=10.0)
    try:
        vals = np.arange(10, dtype=np.float64)
        out = srv.predict({"v": vals}, timeout=30.0)
        assert np.array_equal(out, (vals * 2).astype(np.float32))
        rep = srv.serving_report()
        assert rep["max_batch_occupancy"] == 10
    finally:
        srv.close()


def test_demux_ordering_under_interleaved_threads(monkeypatch):
    """Requests issued from many threads each get their own rows, in their
    own order, regardless of how the dispatcher packed them."""
    fakes = [FakeReplicaHandle("a", delay_s=0.01),
             FakeReplicaHandle("b", delay_s=0.01)]
    srv = _serving(fakes, monkeypatch, timeout_ms=20.0)
    errors = []

    def client(base):
        try:
            vals = np.array([base, base + 0.25, base + 0.5])
            out = srv.predict({"v": vals}, timeout=30.0)
            assert np.array_equal(out, (vals * 2).astype(np.float32))
        except Exception as e:  # noqa: BLE001 - surfaced below
            errors.append(e)

    try:
        threads = [threading.Thread(target=client, args=(float(i),))
                   for i in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30.0)
        assert not errors
        rep = srv.serving_report()
        assert rep["requests"] == 16 and rep["rows"] == 48
    finally:
        srv.close()


def test_routing_spreads_over_replicas(monkeypatch):
    fakes = [FakeReplicaHandle("a"), FakeReplicaHandle("b")]
    srv = _serving(fakes, monkeypatch, max_batch=1, timeout_ms=0.0)
    try:
        for i in range(10):
            srv.predict(_rows(float(i)), timeout=30.0)
        rep = srv.serving_report()
        per = {r["replica"]: r["batches"] for r in rep["replicas"]}
        assert all(n >= 1 for n in per.values()), per
    finally:
        srv.close()


def test_hedging_wins_and_accounts(monkeypatch):
    """A replica that turns slow after warmup gets hedged: the fast sibling
    answers, the request never waits out the straggler, and the counters
    record the race both ways."""
    slow_after = {"n": 0}

    def a_delay():
        slow_after["n"] += 1
        return 0.0 if slow_after["n"] <= 8 else 1.5

    fakes = [FakeReplicaHandle("a", delay_s=a_delay),
             FakeReplicaHandle("b", delay_s=0.0)]
    srv = _serving(fakes, monkeypatch, max_batch=1, timeout_ms=0.0,
                   hedge=True, hedge_mult=2.0, hedge_min_ms=50.0)
    try:
        # warmup: sequential requests alternate replicas, recording >= 8
        # fast batch latencies (the hedge-eligibility floor)
        for i in range(16):
            srv.predict(_rows(float(i)), timeout=30.0)
        # now replica a is a straggler: every request it receives should
        # hedge onto b and complete far below a's 1.5s delay
        t0 = time.monotonic()
        futs = [srv.predict_async(_rows(100.0 + i)) for i in range(4)]
        got = [f.result(timeout=30.0) for f in futs]
        wall = time.monotonic() - t0
        for i, g in enumerate(got):
            assert g[0] == np.float32(2.0 * (100.0 + i))
        assert wall < 1.4, f"hedging did not cut the straggler tail: {wall}"
        rep = srv.serving_report()
        assert rep["hedged"] >= 1
        assert rep["hedge_won"] >= 1
        assert rep["failed"] == 0
        # the losers land ~1.5s later and are discarded+counted
        deadline = time.time() + 5.0
        while time.time() < deadline:
            rep = srv.serving_report()
            if rep["hedge_lost"] >= 1:
                break
            time.sleep(0.1)
        assert rep["hedge_lost"] >= 1
    finally:
        srv.close()


def test_failed_replica_reroutes_and_reloads(monkeypatch):
    """Every request that lands on the scripted-down replica re-routes to
    the live one; the dead replica's background reload is attempted."""
    down = FakeReplicaHandle("a", fail=True)
    up = FakeReplicaHandle("b")
    srv = _serving([down, up], monkeypatch, max_batch=1, timeout_ms=0.0)
    try:
        for i in range(6):
            out = srv.predict(_rows(float(i)), timeout=30.0)
            assert out[0] == np.float32(2.0 * i)
        rep = srv.serving_report()
        assert rep["failed"] == 0
        assert rep["rerouted"] >= 1          # some requests hit the down one
        assert down.loads >= 2               # initial load + reload attempt
    finally:
        srv.close()


def test_app_error_fails_fast_without_reroute(monkeypatch):
    """A deterministic application error (a remote ValueError) must fail
    the request immediately — replaying it on the sibling replica would
    replay the error, and burning the 30s re-route grace on it is the
    failure mode doc/serving.md's table rules out."""
    srv = _serving([FakeReplicaHandle("a", app_fail=True),
                    FakeReplicaHandle("b", app_fail=True)],
                   monkeypatch, max_batch=1, timeout_ms=0.0, grace_s=30.0)
    try:
        t0 = time.monotonic()
        with pytest.raises(ServingError) as ei:
            srv.predict(_rows(1.0), timeout=30.0)
        assert time.monotonic() - t0 < 5.0
        assert "ValueError" in str(ei.value)
        rep = srv.serving_report()
        assert rep["rerouted"] == 0       # never bounced between replicas
    finally:
        srv.close()


def test_reload_rebinds_replica_off_retired_executor(monkeypatch):
    """Satellite (ISSUE 13): the background reload used to probe a FIXED
    executor identity until the re-route grace expired. With the owning
    session's live-member view available, a replica whose executor was
    retired from the pool re-homes onto a surviving member and reloads
    there — requests keep flowing the whole time."""
    from types import SimpleNamespace

    class RetireableHandle(FakeReplicaHandle):
        def __init__(self, name):
            super().__init__(name)
            self.dead = False

        def call(self, method, *args, timeout=None, **kwargs):
            if self.dead:
                raise ConnectionLost(f"{self.name} was retired")
            return super().call(method, *args, timeout=timeout, **kwargs)

        def submit(self, method, *args, **kwargs):
            if self.dead:
                raise ConnectionLost(f"{self.name} was retired")
            return super().submit(method, *args, **kwargs)

    r0 = RetireableHandle("ex0")
    r1 = FakeReplicaHandle("ex1")
    r2 = FakeReplicaHandle("ex2")
    monkeypatch.setenv("RDT_SERVE_MAX_BATCH", "1000")
    monkeypatch.setenv("RDT_SERVE_BATCH_TIMEOUT_MS", "5")
    monkeypatch.setenv("RDT_SERVE_HEDGE", "0")
    monkeypatch.setenv("RDT_SERVE_REROUTE_GRACE_S", "20")
    # the session's live-member view: ex0 already retired, ex2 a survivor
    # that never hosted a replica
    fake_session = SimpleNamespace(executors=[r1, r2])
    srv = ServingSession("/nonexistent/bundle", session=fake_session,
                         executors=[r0, r1], name="t")
    try:
        r0.dead = True  # the retirement lands after construction
        # first dispatch routes to t-r0 (round-robin start), fails, and
        # re-routes; the reload must re-home t-r0 onto ex2 (least loaded
        # live member), not keep dialing the corpse
        out = srv.predict(_rows(1.0, 2.0), timeout=30.0)
        np.testing.assert_allclose(out, [2.0, 4.0])
        deadline = time.time() + 20
        rep0 = None
        while time.time() < deadline:
            rep0 = next(r for r in srv.serving_report()["replicas"]
                        if r["replica"] == "t-r0")
            if rep0["ready"] and rep0["executor"] == "ex2":
                break
            time.sleep(0.1)
        assert rep0 and rep0["executor"] == "ex2", rep0
        assert rep0["ready"], rep0
        assert r2.loads >= 1, "survivor never loaded the re-homed replica"
        # and the re-homed replica serves again
        out2 = srv.predict(_rows(3.0), timeout=30.0)
        np.testing.assert_allclose(out2, [6.0])
        assert srv.serving_report()["failed"] == 0
    finally:
        srv.close()


def test_mixed_schemas_coalesce_separately(monkeypatch):
    """Requests with different schemas in one batching window dispatch as
    separate batches — a mixed concat would fail and punish well-formed
    requests (and, pre-fix, killed the dispatcher thread outright)."""
    srv = _serving([FakeReplicaHandle("a")], monkeypatch, timeout_ms=40.0)
    try:
        f1 = srv.predict_async({"v": np.array([1.0]),
                                "extra": np.array([9.0])})
        f2 = srv.predict_async(_rows(2.0))
        assert f2.result(timeout=30.0)[0] == np.float32(4.0)
        assert f1.result(timeout=30.0)[0] == np.float32(2.0)
        # the session survives and keeps serving
        assert srv.predict(_rows(3.0), timeout=30.0)[0] == np.float32(6.0)
    finally:
        srv.close()


def test_every_replica_down_fails_within_grace(monkeypatch):
    srv = _serving([FakeReplicaHandle("a", fail=True),
                    FakeReplicaHandle("b", fail=True)],
                   monkeypatch, max_batch=1, timeout_ms=0.0, grace_s=1.0)
    try:
        with pytest.raises(ServingError):
            srv.predict(_rows(1.0), timeout=30.0)
        rep = srv.serving_report()
        assert rep["failed"] >= 1
    finally:
        srv.close()


def test_report_columns(monkeypatch):
    srv = _serving([FakeReplicaHandle("a")], monkeypatch)
    try:
        srv.predict(_rows(1.0), timeout=30.0)
        rep = srv.serving_report()
        for col in ("requests", "batches", "rows", "p50_ms", "p99_ms",
                    "mean_batch_occupancy", "max_batch_occupancy",
                    "queue_depth", "queue_depth_peak", "hedged",
                    "hedge_won", "hedge_lost", "rerouted", "failed",
                    "replicas"):
            assert col in rep, col
        assert rep["p99_ms"] >= rep["p50_ms"] >= 0.0
        r0 = rep["replicas"][0]
        for col in ("replica", "executor", "ready", "requests", "batches",
                    "rows", "hedges", "inflight", "inflight_peak",
                    "reloads"):
            assert col in r0, col
    finally:
        srv.close()


def test_closed_session_refuses_and_empty_request_shortcuts(monkeypatch):
    srv = _serving([FakeReplicaHandle("a")], monkeypatch)
    out = srv.predict(_rows(), timeout=5.0)   # 0 rows: answered inline
    assert out.shape == (0,)
    srv.close()
    with pytest.raises(ServingError):
        srv.predict_async(_rows(1.0))
    # post-close report still answers (snapshot, no dispatcher)
    assert "requests" in srv.serving_report()


# ---------------------------------------------------------------------------
# integration: real executors, real estimator, real bundles
# ---------------------------------------------------------------------------

def _linear_data(n=256):
    rng = np.random.RandomState(3)
    x = rng.random_sample((n, 2))
    y = x @ np.array([2.0, -3.0]) + 1.0
    return pd.DataFrame({"x1": x[:, 0], "x2": x[:, 1], "y": y})


@pytest.fixture(scope="module")
def served_model(tmp_path_factory):
    """One 2-executor session + one trained/exported flax estimator shared
    by the integration tests (executor-side jax import paid once)."""
    import optax

    import raydp_tpu
    from raydp_tpu.models import MLP
    from raydp_tpu.train import FlaxEstimator

    s = raydp_tpu.init("serve_it", num_executors=2, executor_cores=1,
                       executor_memory="512MB")
    try:
        pdf = _linear_data()
        df = s.createDataFrame(pdf, num_partitions=2)
        est = FlaxEstimator(
            model=MLP(features=(8,), use_batch_norm=False),
            optimizer=optax.adam(1e-2), loss="mse",
            feature_columns=["x1", "x2"], label_column="y",
            batch_size=64, num_epochs=1)
        est.fit_on_frame(df)
        export_dir = str(tmp_path_factory.mktemp("servable") / "flax")
        est.export_serving(export_dir)
        yield s, est, export_dir, pdf
    finally:
        raydp_tpu.stop()


def test_flax_servable_roundtrip_matches_predict(served_model):
    """load_servable() in-process reproduces estimator.predict bitwise on
    the same rows."""
    from raydp_tpu.data.dataset import from_frame
    from raydp_tpu.serve import load_servable

    s, est, export_dir, pdf = served_model
    sv = load_servable(export_dir)
    table = pa.table({"x1": pdf["x1"].values, "x2": pdf["x2"].values})
    got = sv.predict_table(table)
    df = s.createDataFrame(pdf, num_partitions=2)
    ref = est.predict(from_frame(df.select("x1", "x2")))
    assert np.array_equal(got, ref)


def test_serving_session_row_identical_to_predict(served_model,
                                                  monkeypatch):
    """The acceptance matrix's core equality: concurrent coalesced serving
    returns, per request, exactly the rows a driver-side predict computes —
    coalescing must be invisible in the bits."""
    from raydp_tpu.data.dataset import from_frame
    from raydp_tpu.serve import ServingSession

    s, est, export_dir, pdf = served_model
    df = s.createDataFrame(pdf, num_partitions=2)
    ref = est.predict(from_frame(df.select("x1", "x2")))

    monkeypatch.setenv("RDT_SERVE_BATCH_TIMEOUT_MS", "20")
    monkeypatch.setenv("RDT_SERVE_HEDGE", "0")
    srv = ServingSession(export_dir, session=s, name="it")
    try:
        n = len(pdf)
        futs = [srv.predict_async(
            {"x1": pdf["x1"].values[i:i + 4], "x2": pdf["x2"].values[i:i + 4]})
            for i in range(0, n, 4)]
        got = np.concatenate([f.result(timeout=120.0) for f in futs])
        assert np.array_equal(got, ref)
        rep = srv.serving_report()
        assert rep["requests"] == n // 4
        assert rep["batches"] < rep["requests"]   # coalescing on real RPCs
        assert rep["failed"] == 0
        assert sum(r["batches"] for r in rep["replicas"]) == rep["batches"]
    finally:
        srv.close()


def test_serve_stats_and_unload(served_model, monkeypatch):
    from raydp_tpu.serve import ServingSession

    s, _est, export_dir, pdf = served_model
    monkeypatch.setenv("RDT_SERVE_HEDGE", "0")
    srv = ServingSession(export_dir, session=s, name="stats")
    try:
        srv.predict({"x1": pdf["x1"].values[:8],
                     "x2": pdf["x2"].values[:8]}, timeout=60.0)
        stats = s.executors[0].call("serve_stats")
        mine = [r for r in stats["replicas"]
                if r["replica"].startswith("stats-")]
        assert mine and mine[0]["model_nbytes"] > 0
    finally:
        srv.close()
    # after close(unload=True) the replicas are gone from the registry
    stats = s.executors[0].call("serve_stats")
    assert not any(r["replica"].startswith("stats-")
                   for r in stats["replicas"])


def test_replica_not_loaded_is_typed(served_model):
    s, _est, _export_dir, _pdf = served_model
    with pytest.raises(RemoteError) as ei:
        s.executors[0].call("serve_predict", "no-such-replica", b"")
    assert ei.value.exc_type == "ReplicaNotLoaded"


def test_keras_servable_roundtrip(served_model, tmp_path):
    """Keras export → load_servable reproduces KerasEstimator.predict
    bitwise (architecture from the pickled model, weights from the
    checkpoint). Rides the shared session — init() is a singleton."""
    keras = pytest.importorskip("keras")
    from raydp_tpu.data.dataset import from_frame
    from raydp_tpu.serve import load_servable
    from raydp_tpu.train import KerasEstimator

    s, _est, _export_dir, pdf = served_model
    df = s.createDataFrame(pdf.iloc[:128], num_partitions=1)
    model = keras.Sequential([
        keras.layers.Input((2,)),
        keras.layers.Dense(4, activation="relu"),
        keras.layers.Dense(1),
    ])
    model.compile(optimizer="adam", loss="mse")
    est = KerasEstimator(model=model, feature_columns=["x1", "x2"],
                         label_column="y", batch_size=64, num_epochs=1)
    est.fit_on_frame(df)
    export_dir = str(tmp_path / "keras-bundle")
    est.export_serving(export_dir)
    sv = load_servable(export_dir)
    got = sv.predict_table(
        pa.table({"x1": pdf["x1"].values[:128], "x2": pdf["x2"].values[:128]}))
    ref = est.predict(from_frame(df.select("x1", "x2")))
    assert np.array_equal(got, ref)


def test_export_requires_fit(tmp_path):
    import optax

    from raydp_tpu.models import MLP
    from raydp_tpu.train import FlaxEstimator
    from raydp_tpu.train.gbdt_estimator import GBDTEstimator

    est = FlaxEstimator(model=MLP(features=(4,), use_batch_norm=False),
                        optimizer=optax.adam(1e-2),
                        feature_columns=["a"], label_column="b")
    with pytest.raises(RuntimeError):
        est.export_serving(str(tmp_path / "x"))
    with pytest.raises(NotImplementedError):
        GBDTEstimator(feature_columns=["a"],
                      label_column="b").export_serving(str(tmp_path / "y"))


# ---------------------------------------------------------------------------
# overload shedding (ISSUE 14): typed rejections, dispatcher stays alive
# ---------------------------------------------------------------------------

def test_overload_sheds_typed_and_dispatcher_survives(monkeypatch):
    """Past RDT_SERVE_MAX_QUEUE outstanding requests predict_async fails
    fast with the typed retriable ServingOverloaded; accepted requests
    keep serving byte-correct results, the report shows failed == shed,
    and — the retriable contract — the session accepts again once the
    queue drains."""
    from raydp_tpu.serve import ServingOverloaded

    monkeypatch.setenv("RDT_SERVE_MAX_QUEUE", "4")
    slow = FakeReplicaHandle("a", delay_s=0.25)
    srv = _serving([slow], monkeypatch, max_batch=1, timeout_ms=0.0,
                   inflight=1)
    try:
        futs, sheds = [], 0
        for i in range(12):
            try:
                futs.append((i, srv.predict_async(_rows(float(i)))))
            except ServingOverloaded as e:
                assert isinstance(e, ServingError)  # subclass: one catch
                sheds += 1
        assert sheds >= 1, "queue bound never shed"
        assert len(futs) >= 4  # the bound's worth was accepted
        for i, f in futs:
            got = f.result(timeout=30.0)
            assert got[0] == np.float32(2.0 * i)  # accepted = byte-correct
        rep = srv.serving_report()
        assert rep["shed"] == sheds
        assert rep["failed"] == rep["shed"], rep  # failed == shed ONLY
        assert rep["outstanding"] == 0
        assert rep["max_queue"] == 4
        # retriable: the drained session accepts and serves again
        assert srv.predict(_rows(99.0), timeout=30.0)[0] \
            == np.float32(198.0)
    finally:
        srv.close()


def test_overload_shed_disabled_by_zero(monkeypatch):
    monkeypatch.setenv("RDT_SERVE_MAX_QUEUE", "0")
    srv = _serving([FakeReplicaHandle("a", delay_s=0.05)], monkeypatch,
                   max_batch=1, timeout_ms=0.0, inflight=1)
    try:
        futs = [srv.predict_async(_rows(float(i))) for i in range(32)]
        for i, f in enumerate(futs):
            assert f.result(timeout=30.0)[0] == np.float32(2.0 * i)
        rep = srv.serving_report()
        assert rep["shed"] == 0 and rep["failed"] == 0
    finally:
        srv.close()


def test_hedging_suppressed_while_shedding(monkeypatch):
    """A saturated session must not hedge: the duplicate dispatch would
    amplify the very overload being shed. The same straggler that hedges
    under an uncontended queue rides out its full delay when the
    outstanding queue sits at the bound."""
    for max_queue, expect_hedge in (("100", True), ("1", False)):
        monkeypatch.setenv("RDT_SERVE_MAX_QUEUE", max_queue)
        slow_after = {"n": 0}

        def a_delay():
            slow_after["n"] += 1
            return 0.0 if slow_after["n"] <= 8 else 1.0

        fakes = [FakeReplicaHandle("a", delay_s=a_delay),
                 FakeReplicaHandle("b", delay_s=0.0)]
        srv = _serving(fakes, monkeypatch, max_batch=1, timeout_ms=0.0,
                       hedge=True, hedge_mult=2.0, hedge_min_ms=50.0)
        try:
            for i in range(16):  # warmup: record the fast latency floor
                srv.predict(_rows(float(i)), timeout=30.0)
            # one straggler dispatch; with max_queue=1 the lone
            # outstanding request saturates the session
            t0 = time.monotonic()
            while True:  # land a request on the (now slow) replica a
                got = srv.predict(_rows(123.0), timeout=30.0)
                if slow_after["n"] > 9:
                    break
            wall = time.monotonic() - t0
            assert got[0] == np.float32(246.0)
            rep = srv.serving_report()
            if expect_hedge:
                assert rep["hedged"] >= 1, (max_queue, rep)
            else:
                assert rep["hedged"] == 0, (max_queue, rep)
                assert wall >= 0.9, "suppressed hedge still cut the tail?"
        finally:
            srv.close()


# ---------------------------------------------------------------------------
# hot swap (ISSUE 15): versioned servables under live traffic
# ---------------------------------------------------------------------------

class VersionedFakeReplica(FakeReplicaHandle):
    """A fake whose answer depends on the LOADED bundle: a replica id
    loaded from ``.../vN`` answers ``(N + 1) * v`` — so every response
    names the exact servable version that computed it."""

    def __init__(self, name, delay_s=0.0):
        super().__init__(name, delay_s=delay_s)
        self.dirs: dict = {}        # rid -> export dir
        self.unloaded: list = []

    def call(self, method, *args, timeout=None, **kwargs):
        if method == "serve_unload":
            with self._lock:
                self.dirs.pop(args[0], None)
                self.unloaded.append(args[0])
            return True
        return super().call(method, *args, timeout=timeout, **kwargs)

    def submit(self, method, *args, **kwargs):
        if method == "serve_load":
            rid, export_dir = args
            with self._lock:
                self.dirs[rid] = export_dir
                self.loads += 1
            fut = Future()
            fut.set_result({"replica": rid})
            return fut
        assert method == "serve_predict"
        rid, payload = args
        with self._lock:
            self.calls += 1
            mult = int(self.dirs[rid].rsplit("v", 1)[1]) + 1
        fut = Future()
        threading.Thread(target=self._serve_versioned,
                         args=(payload, fut, mult), daemon=True).start()
        return fut

    def _serve_versioned(self, payload, fut, mult):
        d = self.delay_s() if callable(self.delay_s) else self.delay_s
        if d:
            time.sleep(d)
        table = _decode_payload(payload)
        v = table.column("v").to_numpy(zero_copy_only=False)
        fut.set_result((v * float(mult)).astype(np.float32))


def test_hot_swap_shifts_traffic_and_reports_active_version(monkeypatch):
    monkeypatch.setenv("RDT_SERVE_BATCH_TIMEOUT_MS", "5")
    monkeypatch.setenv("RDT_SERVE_HEDGE", "0")
    monkeypatch.setenv("RDT_SERVE_SWAP_DRAIN_S", "5")
    reps = [VersionedFakeReplica("a"), VersionedFakeReplica("b")]
    srv = ServingSession("/bundles/v1", executors=reps, name="hs")
    try:
        assert np.array_equal(srv.predict(_rows(1.0, 2.0)), [2.0, 4.0])
        rep = srv.serving_report()
        assert rep["servable"] == {"version": 1,
                                   "export_dir": "/bundles/v1",
                                   "tag": None}
        info = srv.hot_swap("/bundles/v2", tag="epoch-9")
        assert info["version"] == 2
        assert info["replicas"] == ["hs-v2-r0", "hs-v2-r1"]
        # every post-swap dispatch answers from v2
        assert np.array_equal(srv.predict(_rows(1.0, 2.0)), [3.0, 6.0])
        rep = srv.serving_report()
        assert rep["servable"] == {"version": 2,
                                   "export_dir": "/bundles/v2",
                                   "tag": "epoch-9"}
        assert rep["hot_swaps"] == 1
        # the old version retires (drained: nothing in flight on it)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline \
                and not all(h.unloaded for h in reps):
            time.sleep(0.02)
        assert [u for h in reps for u in h.unloaded] \
            == ["hs-r0", "hs-r1"]
    finally:
        srv.close()


def test_hot_swap_racing_predict_burst_zero_dropped(monkeypatch):
    """The ISSUE 15 race leg at unit precision: a predict burst straddles
    two hot-swaps while the outgoing version still holds in-flight work
    (a scripted apply delay) — zero dropped requests, and every response
    is the output of exactly ONE servable version (2v, 3v or 4v — never a
    mix within one request, never a value from no version)."""
    monkeypatch.setenv("RDT_SERVE_BATCH_TIMEOUT_MS", "2")
    monkeypatch.setenv("RDT_SERVE_HEDGE", "0")
    monkeypatch.setenv("RDT_SERVE_SWAP_DRAIN_S", "3")
    reps = [VersionedFakeReplica("a", delay_s=0.01),
            VersionedFakeReplica("b", delay_s=0.01)]
    srv = ServingSession("/bundles/v1", executors=reps, name="race")
    try:
        stop = threading.Event()
        futs, errors = [], []

        def fire():
            i = 0
            while not stop.is_set():
                try:
                    futs.append((float(i), srv.predict_async(
                        _rows(float(i)))))
                except Exception as e:  # noqa: BLE001 - counted
                    errors.append(repr(e))
                i += 1
                time.sleep(0.001)

        t = threading.Thread(target=fire)
        t.start()
        time.sleep(0.05)
        srv.hot_swap("/bundles/v2", tag="epoch-2")
        time.sleep(0.05)
        srv.hot_swap("/bundles/v3", tag="epoch-4")
        time.sleep(0.05)
        stop.set()
        t.join(timeout=30)
        assert not errors, errors
        assert len(futs) > 20
        versions = set()
        for v, f in futs:
            got = f.result(timeout=30.0)
            assert got.shape == (1,)
            if v == 0.0:
                continue  # 0 is version-blind
            mult = got[0] / v
            # exactly one version answered: the multiplier is one of the
            # three loaded servables', bit-exact
            assert mult in (2.0, 3.0, 4.0), (v, got)
            versions.add(mult)
        assert len(versions) >= 2, "burst never straddled a swap"
        rep = srv.serving_report()
        assert rep["hot_swaps"] == 2
        assert rep["failed"] == 0 and rep["shed"] == 0
        assert rep["servable"]["version"] == 3
        assert rep["servable"]["tag"] == "epoch-4"
    finally:
        srv.close()


def test_hot_swap_drain_waits_for_inflight_then_unloads(monkeypatch):
    """Retirement semantics: the outgoing version's in-flight dispatch
    completes (no drop), and its replicas unload only after the drain."""
    monkeypatch.setenv("RDT_SERVE_BATCH_TIMEOUT_MS", "2")
    monkeypatch.setenv("RDT_SERVE_HEDGE", "0")
    monkeypatch.setenv("RDT_SERVE_SWAP_DRAIN_S", "10")
    slow = VersionedFakeReplica("slow", delay_s=0.3)
    srv = ServingSession("/bundles/v1", executors=[slow], name="drain")
    try:
        f = srv.predict_async(_rows(5.0))   # in flight on v1, 300ms apply
        time.sleep(0.05)
        srv.hot_swap("/bundles/v2")
        assert not slow.unloaded            # v1 still busy: not retired yet
        assert np.array_equal(f.result(timeout=30.0), [10.0])  # v1 answered
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and not slow.unloaded:
            time.sleep(0.02)
        assert slow.unloaded == ["drain-r0"]
        assert np.array_equal(srv.predict(_rows(5.0)), [15.0])  # v2 now
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# guarded rollouts (ISSUE 18): weighted versions, judgment, autoscale
# ---------------------------------------------------------------------------

def _mult_of(got, vals):
    """The single servable multiplier a whole response came from — raises
    if the rows disagree (a response mixing versions is the bug)."""
    mults = {round(float(g) / float(v), 6) for g, v in zip(got, vals) if v}
    assert len(mults) == 1, f"response mixed versions: {mults}"
    return mults.pop()


def test_weighted_routing_splits_deterministically(monkeypatch):
    """Smooth WRR at weights 1.0 : 0.5 gives an exact 2:1 dispatch split —
    no RNG, so the counts are pinned, not statistical."""
    monkeypatch.setenv("RDT_SERVE_HEDGE", "0")
    reps = [VersionedFakeReplica("a"), VersionedFakeReplica("b")]
    srv = ServingSession("/bundles/v1", executors=reps, name="w")
    try:
        srv.load_version("/bundles/v2", weight=0.5, tag="canary")
        counts = {2.0: 0, 3.0: 0}
        for i in range(1, 31):  # sequential: one dispatch per request
            got = srv.predict(_rows(float(i)), timeout=30.0)
            counts[_mult_of(got, [float(i)])] += 1
        assert counts == {2.0: 20, 3.0: 10}, counts
        rep = srv.serving_report()
        rows = {v["version"]: v for v in rep["versions"]}
        assert rows[1]["primary"] and not rows[2]["primary"]
        assert rows[1]["weight"] == 1.0 and rows[2]["weight"] == 0.5
        assert rows[1]["requests"] == 20 and rows[2]["requests"] == 10
        assert rows[2]["tag"] == "canary"
        assert rows[1]["lat_n"] == 20 and rows[2]["lat_n"] == 10
        # primary view (back-compat surfaces) unchanged by a live canary
        assert rep["servable"]["version"] == 1
    finally:
        srv.close()


def test_multi_row_requests_never_split_across_versions(monkeypatch):
    """A coalesced batch (and therefore every response demuxed from it)
    is computed by exactly one version, even at a 50/50 split."""
    monkeypatch.setenv("RDT_SERVE_BATCH_TIMEOUT_MS", "10")
    monkeypatch.setenv("RDT_SERVE_HEDGE", "0")
    reps = [VersionedFakeReplica("a", delay_s=0.005),
            VersionedFakeReplica("b", delay_s=0.005)]
    srv = ServingSession("/bundles/v1", executors=reps, name="nosplit")
    try:
        srv.load_version("/bundles/v2", weight=1.0)
        futs = []
        for i in range(1, 25):
            vals = [float(i), float(i) + 0.25, float(i) + 0.5]
            futs.append((vals, srv.predict_async({"v": np.array(vals)})))
        seen = set()
        for vals, f in futs:
            got = f.result(timeout=30.0)
            seen.add(_mult_of(got, vals))  # raises on any within-row mix
        assert seen == {2.0, 3.0}, seen    # both versions took traffic
        rep = srv.serving_report()
        assert rep["failed"] == 0
    finally:
        srv.close()


def test_weight_zero_parks_version_out_of_new_traffic(monkeypatch):
    monkeypatch.setenv("RDT_SERVE_HEDGE", "0")
    reps = [VersionedFakeReplica("a")]
    srv = ServingSession("/bundles/v1", executors=reps, name="wz")
    try:
        srv.load_version("/bundles/v2", weight=1.0)
        srv.set_weight(2, 0.0)
        for i in range(1, 9):
            got = srv.predict(_rows(float(i)), timeout=30.0)
            assert _mult_of(got, [float(i)]) == 2.0  # primary only
        # still live (not unloaded), just weightless
        assert {v["version"] for v in srv.serving_report()["versions"]} \
            == {1, 2}
        with pytest.raises(ServingError):
            srv.set_weight(99, 0.5)
    finally:
        srv.close()


def test_hedge_requires_sibling_within_version(monkeypatch):
    """Hedges are version-local: two single-replica versions hold two
    replicas total, but neither version has a sibling, so a straggler must
    NOT hedge across versions (a canary answering a baseline request is
    the contamination this pins)."""
    slow_after = {"n": 0}

    def a_delay():
        slow_after["n"] += 1
        return 0.0 if slow_after["n"] <= 10 else 0.4

    rep = VersionedFakeReplica("a", delay_s=a_delay)
    monkeypatch.setenv("RDT_SERVE_MAX_BATCH", "1")
    monkeypatch.setenv("RDT_SERVE_BATCH_TIMEOUT_MS", "0")
    monkeypatch.setenv("RDT_SERVE_HEDGE", "1")
    monkeypatch.setenv("RDT_SERVE_HEDGE_QUANTILE", "0.5")
    monkeypatch.setenv("RDT_SERVE_HEDGE_MULTIPLIER", "2.0")
    monkeypatch.setenv("RDT_SERVE_HEDGE_MIN_MS", "50")
    srv = ServingSession("/bundles/v1", executors=[rep], name="hl")
    try:
        srv.load_version("/bundles/v2", weight=1.0)
        for i in range(1, 11):  # warm the latency window
            srv.predict(_rows(float(i)), timeout=30.0)
        got = srv.predict(_rows(7.0), timeout=30.0)  # the straggler
        assert _mult_of(got, [7.0]) in (2.0, 3.0)
        assert srv.serving_report()["hedged"] == 0
    finally:
        srv.close()


def test_hedged_canary_stays_canary(monkeypatch):
    """With the canary at full weight and a straggling canary replica, the
    hedge races the canary's OWN sibling — the answer keeps the canary's
    multiplier bit-exact."""
    slow_after = {"n": 0}

    def a_delay():
        slow_after["n"] += 1
        return 0.0 if slow_after["n"] <= 12 else 1.0

    reps = [VersionedFakeReplica("a", delay_s=a_delay),
            VersionedFakeReplica("b")]
    monkeypatch.setenv("RDT_SERVE_MAX_BATCH", "1")
    monkeypatch.setenv("RDT_SERVE_BATCH_TIMEOUT_MS", "0")
    monkeypatch.setenv("RDT_SERVE_HEDGE", "1")
    monkeypatch.setenv("RDT_SERVE_HEDGE_QUANTILE", "0.5")
    monkeypatch.setenv("RDT_SERVE_HEDGE_MULTIPLIER", "2.0")
    monkeypatch.setenv("RDT_SERVE_HEDGE_MIN_MS", "50")
    srv = ServingSession("/bundles/v1", executors=reps, name="hc")
    try:
        srv.load_version("/bundles/v2", weight=1.0)
        srv.set_weight(1, 0.0)  # all traffic to the canary
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            got = srv.predict(_rows(3.0), timeout=30.0)
            assert _mult_of(got, [3.0]) == 3.0  # never the baseline's 2.0
            if srv.serving_report()["hedged"] >= 1:
                break
        rep = srv.serving_report()
        assert rep["hedged"] >= 1, "straggler never hedged"
        assert rep["failed"] == 0
    finally:
        srv.close()


def test_promote_version_retires_old_primary(monkeypatch):
    monkeypatch.setenv("RDT_SERVE_HEDGE", "0")
    monkeypatch.setenv("RDT_SERVE_SWAP_DRAIN_S", "5")
    reps = [VersionedFakeReplica("a"), VersionedFakeReplica("b")]
    srv = ServingSession("/bundles/v1", executors=reps, name="pr")
    try:
        srv.load_version("/bundles/v2", weight=0.25, tag="canary")
        info = srv.promote_version(2)
        assert info["retired"] == 1
        rep = srv.serving_report()
        assert rep["servable"] == {"version": 2,
                                   "export_dir": "/bundles/v2",
                                   "tag": "canary"}
        assert rep["hot_swaps"] == 1  # rides the swap counter contract
        assert [v["version"] for v in rep["versions"]] == [2]
        for i in range(1, 6):
            got = srv.predict(_rows(float(i)), timeout=30.0)
            assert _mult_of(got, [float(i)]) == 3.0
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline \
                and not all(h.unloaded for h in reps):
            time.sleep(0.02)
        assert [u for h in reps for u in h.unloaded] == ["pr-r0", "pr-r1"]
    finally:
        srv.close()


def test_drop_version_unloads_canary_and_rehomes_nothing(monkeypatch):
    monkeypatch.setenv("RDT_SERVE_HEDGE", "0")
    monkeypatch.setenv("RDT_SERVE_SWAP_DRAIN_S", "5")
    reps = [VersionedFakeReplica("a")]
    srv = ServingSession("/bundles/v1", executors=reps, name="dr")
    try:
        srv.load_version("/bundles/v2", weight=0.5)
        with pytest.raises(ServingError):
            srv.drop_version(1)  # the primary is not droppable
        srv.drop_version(2)
        for i in range(1, 7):
            got = srv.predict(_rows(float(i)), timeout=30.0)
            assert _mult_of(got, [float(i)]) == 2.0  # primary serves on
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and not reps[0].unloaded:
            time.sleep(0.02)
        assert reps[0].unloaded == ["dr-v2-r0"]
        assert [v["version"]
                for v in srv.serving_report()["versions"]] == [1]
    finally:
        srv.close()


class FailingVersionReplica(VersionedFakeReplica):
    """Replica ids matching ``fail_substr`` answer with the chaos plane's
    transient InjectedFault (re-routable) — every replica of that version
    refuses, so its dispatches exhaust the version-local re-route and
    fail, exactly the error-rate shape a regressing canary produces."""

    def __init__(self, name, fail_substr):
        super().__init__(name)
        self.fail_substr = fail_substr

    def submit(self, method, *args, **kwargs):
        if method == "serve_predict" and self.fail_substr in args[0]:
            with self._lock:
                self.calls += 1
            fut = Future()

            def _fail():
                time.sleep(0.005)
                fut.set_exception(
                    RemoteError("InjectedFault", "scripted canary fault",
                                "<tb>"))

            threading.Thread(target=_fail, daemon=True).start()
            return fut
        return super().submit(method, *args, **kwargs)


def _traffic(srv, stop, errors, period_s=0.004):
    """Open-loop background load for rollout tests; ServingError is the
    expected casualty of a scripted-to-fail canary, anything else isn't."""
    i = 0
    while not stop.is_set():
        try:
            srv.predict_async(_rows(float(i % 50 + 1)))
        except ServingError:
            pass
        except Exception as e:  # noqa: BLE001 - surfaced by the test
            errors.append(repr(e))
        i += 1
        time.sleep(period_s)


def test_rollout_promotes_healthy_canary_under_traffic(monkeypatch):
    monkeypatch.setenv("RDT_SERVE_BATCH_TIMEOUT_MS", "2")
    monkeypatch.setenv("RDT_SERVE_HEDGE", "0")
    monkeypatch.setenv("RDT_SERVE_SWAP_DRAIN_S", "3")
    reps = [VersionedFakeReplica("a"), VersionedFakeReplica("b")]
    srv = ServingSession("/bundles/v1", executors=reps, name="ro")
    stop, errors = threading.Event(), []
    t = threading.Thread(target=_traffic, args=(srv, stop, errors))
    t.start()
    try:
        out = srv.rollout("/bundles/v2", tag="epoch-1",
                          initial_weight=0.5, steps=[1.0], step_s=5.0,
                          min_samples=8)
        assert out["outcome"] == "promoted", out
        assert out["version"] == 2
        assert any(s["verdict"] == "healthy" for s in out["steps"])
        rep = srv.serving_report()
        assert rep["servable"]["version"] == 2
        assert rep["servable"]["tag"] == "epoch-1"
        assert rep["hot_swaps"] == 1
    finally:
        stop.set()
        t.join(timeout=30)
        srv.close()
    assert not errors, errors


def test_rollout_rolls_back_on_canary_error_rate(monkeypatch):
    """The canary's replicas fail every dispatch (transient InjectedFault:
    re-routed version-locally, exhausted, counted per-version) — the
    judgment sees its error rate, rolls back, and the baseline keeps
    serving untouched; run() RETURNS the outcome rather than raising."""
    monkeypatch.setenv("RDT_SERVE_BATCH_TIMEOUT_MS", "2")
    monkeypatch.setenv("RDT_SERVE_HEDGE", "0")
    monkeypatch.setenv("RDT_SERVE_SWAP_DRAIN_S", "3")
    monkeypatch.setenv("RDT_SERVE_REROUTE_GRACE_S", "0.4")
    reps = [FailingVersionReplica("a", "-v2-"),
            FailingVersionReplica("b", "-v2-")]
    srv = ServingSession("/bundles/v1", executors=reps, name="rb")
    stop, errors = threading.Event(), []
    t = threading.Thread(target=_traffic, args=(srv, stop, errors))
    t.start()
    try:
        out = srv.rollout("/bundles/v2", initial_weight=0.5,
                          steps=[1.0], step_s=15.0, min_samples=6,
                          err_tol=0.05)
        assert out["outcome"] == "rolled_back", out
        assert "error rate" in out["reason"]
        rep = srv.serving_report()
        assert rep["servable"]["version"] == 1   # baseline untouched
        assert [v["version"] for v in rep["versions"]] == [1]
        assert rep["hot_swaps"] == 0
        deadline = time.monotonic() + 5
        want = {"rb-v2-r0", "rb-v2-r1"}
        while time.monotonic() < deadline:
            got = {u for h in reps for u in h.unloaded}
            if want <= got:
                break
            time.sleep(0.02)
        assert want <= {u for h in reps for u in h.unloaded}
        from raydp_tpu import metrics
        assert any(e["kind"] == "rollout_rollback"
                   for e in metrics.events())
    finally:
        stop.set()
        t.join(timeout=30)
        srv.close()
    assert not errors, errors
    # post-rollback: the baseline still answers bit-correct
    # (session closed above, so assert on the collected report instead)
    assert rep["versions"][0]["failed"] == 0


def test_rollout_advances_without_traffic(monkeypatch):
    """An idle session must still deploy: a step whose judgment window
    never fills advances vacuously (insufficient traffic is no evidence
    of regression)."""
    monkeypatch.setenv("RDT_SERVE_HEDGE", "0")
    monkeypatch.setenv("RDT_SERVE_SWAP_DRAIN_S", "3")
    reps = [VersionedFakeReplica("a")]
    srv = ServingSession("/bundles/v1", executors=reps, name="idle")
    try:
        out = srv.rollout("/bundles/v2", initial_weight=0.25,
                          steps=[1.0], step_s=0.15, min_samples=1000)
        assert out["outcome"] == "promoted", out
        assert all(s["verdict"] == "insufficient" for s in out["steps"])
        assert srv.serving_report()["servable"]["version"] == 2
    finally:
        srv.close()


def test_rollout_judgment_suspended_while_shedding():
    """The false-positive the design must not have: identical (terrible)
    canary numbers are 'unhealthy' under normal load but 'suspended' while
    the shedding gate is active — saturation inflates both versions, so no
    verdict is allowed."""
    from raydp_tpu.serve.rollout import RolloutController

    ctl = RolloutController.__new__(RolloutController)
    ctl.min_samples = 4
    ctl.err_tol = 0.02
    ctl.p99_factor = 2.0
    base0 = {"requests": 0, "failed": 0, "p99_ms": 5.0, "lat_n": 50}
    can0 = {"requests": 0, "failed": 0, "p99_ms": 50.0, "lat_n": 50}
    base1 = {"requests": 100, "failed": 0, "p99_ms": 5.0, "lat_n": 50}
    can1 = {"requests": 2, "failed": 20, "p99_ms": 50.0, "lat_n": 50}
    assert ctl._judge(base0, can0, base1, can1,
                      shedding=False)["verdict"] == "unhealthy"
    assert ctl._judge(base0, can0, base1, can1,
                      shedding=True)["verdict"] == "suspended"
    # and the latency arm alone also kills it once windows are full
    can_lat = {"requests": 100, "failed": 0, "p99_ms": 50.0, "lat_n": 50}
    v = ctl._judge(base0, can0, base1, can_lat, shedding=False)
    assert v["verdict"] == "unhealthy" and "p99" in v["reason"]
    # below the min-sample floor: no verdict either way
    tiny = {"requests": 2, "failed": 1, "p99_ms": 50.0, "lat_n": 2}
    assert ctl._judge(base0, can0, base1, tiny,
                      shedding=False)["verdict"] == "insufficient"


def test_scale_replicas_grows_and_shrinks_every_version(monkeypatch):
    monkeypatch.setenv("RDT_SERVE_HEDGE", "0")
    monkeypatch.setenv("RDT_SERVE_SWAP_DRAIN_S", "2")
    reps = [VersionedFakeReplica("a"), VersionedFakeReplica("b")]
    srv = ServingSession("/bundles/v1", executors=reps, name="sc")
    try:
        srv.load_version("/bundles/v2", weight=0.5)
        out = srv.scale_replicas(3)
        assert out["replicas"] == 3
        rep = srv.serving_report()
        assert all(v["replicas"] == 3 for v in rep["versions"]), rep
        rids = {r["replica"] for r in rep["replicas"]}
        assert {"sc-v1-r2", "sc-v2-r2"} <= rids  # scale-up id namespace
        for i in range(1, 13):  # the grown fleet serves, both versions
            got = srv.predict(_rows(float(i)), timeout=30.0)
            assert _mult_of(got, [float(i)]) in (2.0, 3.0)
        srv.scale_replicas(1)
        rep = srv.serving_report()
        assert all(v["replicas"] == 1 for v in rep["versions"]), rep
        deadline = time.monotonic() + 5  # drained victims unload
        while time.monotonic() < deadline \
                and sum(len(h.unloaded) for h in reps) < 4:
            time.sleep(0.02)
        assert sum(len(h.unloaded) for h in reps) == 4
        assert srv.predict(_rows(2.0), timeout=30.0).shape == (1,)
    finally:
        srv.close()


def test_serving_autoscaler_grows_on_pressure_then_drains(monkeypatch):
    """The PR 13 controller shape on serving signals: sustained queue
    pressure grows every version's replica count before the shed bound,
    sustained idleness drains back to the floor, cooldown between."""
    from raydp_tpu.serve import ServingAutoscaler

    monkeypatch.setenv("RDT_SERVE_MAX_BATCH", "1")
    monkeypatch.setenv("RDT_SERVE_BATCH_TIMEOUT_MS", "0")
    monkeypatch.setenv("RDT_SERVE_HEDGE", "0")
    monkeypatch.setenv("RDT_SERVE_MAX_INFLIGHT", "1")
    monkeypatch.setenv("RDT_SERVE_SCALE_INTERVAL_S", "0.05")
    monkeypatch.setenv("RDT_SERVE_SCALE_UP_S", "0.1")
    monkeypatch.setenv("RDT_SERVE_SCALE_IDLE_S", "0.4")
    monkeypatch.setenv("RDT_SERVE_SCALE_COOLDOWN_S", "0.1")
    monkeypatch.setenv("RDT_SERVE_SWAP_DRAIN_S", "2")

    class SerialVersionedReplica(VersionedFakeReplica):
        """A real replica serves its loop serially — the fake must too, or
        a 60-dispatch burst drains in one delay and no pressure sustains."""

        _serial = threading.Lock()

        def _serve_versioned(self, payload, fut, mult):
            with self._serial:
                super()._serve_versioned(payload, fut, mult)

    rep = SerialVersionedReplica("a", delay_s=0.03)
    srv = ServingSession("/bundles/v1", executors=[rep], name="as")
    scaler = ServingAutoscaler(srv, min_replicas=1, max_replicas=3).start()
    try:
        futs = [srv.predict_async(_rows(float(i + 1))) for i in range(60)]
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            if any(e["direction"] == "up" for e in scaler.events):
                break
            time.sleep(0.02)
        assert any(e["direction"] == "up" for e in scaler.events), \
            scaler.events
        for i, f in enumerate(futs):  # burst fully served, bit-correct
            assert f.result(timeout=30.0)[0] == np.float32(2.0 * (i + 1))
        deadline = time.monotonic() + 20  # idle: drain back to the floor
        while time.monotonic() < deadline:
            vrow = srv.serving_report()["versions"][0]
            if vrow["replicas"] == 1:
                break
            time.sleep(0.05)
        assert srv.serving_report()["versions"][0]["replicas"] == 1, \
            scaler.events
        assert any(e["direction"] == "down" for e in scaler.events)
    finally:
        scaler.stop()
        srv.close()


def test_hot_swap_racing_overload_shed(monkeypatch):
    """ISSUE 18 satellite: a swap during a saturated burst. Accepted
    requests all complete from exactly one version, sheds stay typed
    (failed == shed), and the outgoing version's replicas unload within
    the drain bound — no replica leak behind the shed wall."""
    monkeypatch.setenv("RDT_SERVE_MAX_QUEUE", "6")
    monkeypatch.setenv("RDT_SERVE_MAX_BATCH", "1")
    monkeypatch.setenv("RDT_SERVE_BATCH_TIMEOUT_MS", "0")
    monkeypatch.setenv("RDT_SERVE_HEDGE", "0")
    monkeypatch.setenv("RDT_SERVE_MAX_INFLIGHT", "1")
    monkeypatch.setenv("RDT_SERVE_SWAP_DRAIN_S", "2")
    from raydp_tpu.serve import ServingOverloaded

    reps = [VersionedFakeReplica("a", delay_s=0.02)]
    srv = ServingSession("/bundles/v1", executors=reps, name="swsh")
    try:
        accepted, sheds, errors = [], [0], []
        stop = threading.Event()

        def flood():
            i = 0
            while not stop.is_set():
                try:
                    accepted.append((float(i % 40 + 1), srv.predict_async(
                        _rows(float(i % 40 + 1)))))
                except ServingOverloaded:
                    sheds[0] += 1
                except Exception as e:  # noqa: BLE001 - counted
                    errors.append(repr(e))
                i += 1
                time.sleep(0.001)

        t = threading.Thread(target=flood)
        t.start()
        time.sleep(0.1)
        srv.hot_swap("/bundles/v2", tag="mid-burst")  # racing saturation
        time.sleep(0.1)
        stop.set()
        t.join(timeout=30)
        assert not errors, errors
        assert sheds[0] >= 1, "burst never saturated the queue"
        for v, f in accepted:  # zero dropped accepted requests
            got = f.result(timeout=30.0)
            assert _mult_of(got, [v]) in (2.0, 3.0)
        deadline = time.monotonic() + 8  # v1 must not leak past the drain
        while time.monotonic() < deadline \
                and "swsh-r0" not in reps[0].unloaded:
            time.sleep(0.02)
        assert "swsh-r0" in reps[0].unloaded
        rep = srv.serving_report()
        assert rep["failed"] == rep["shed"] >= 1
        assert rep["servable"]["version"] == 2
        assert rep["retiring_replicas"] == 0
    finally:
        srv.close()


class RestartingUnloadReplica(VersionedFakeReplica):
    """serve_unload refuses (ConnectionLost) for the first ``refuse`` calls
    per rid — the executor-mid-restart shape the retry path exists for."""

    def __init__(self, name, refuse=2):
        super().__init__(name)
        self.refuse = refuse
        self.unload_attempts: dict = {}

    def call(self, method, *args, timeout=None, **kwargs):
        if method == "serve_unload":
            rid = args[0]
            with self._lock:
                n = self.unload_attempts.get(rid, 0) + 1
                self.unload_attempts[rid] = n
            if n <= self.refuse:
                raise ConnectionLost(f"{self.name} restarting")
        return super().call(method, *args, timeout=timeout, **kwargs)


def test_retired_unload_retries_through_restart(monkeypatch):
    """ISSUE 18 satellite: retirement unloads RETRY through the probe
    path — an executor that refuses twice mid-restart still gets its
    registry entry dropped, with no unload_failed leak recorded."""
    from raydp_tpu import metrics
    monkeypatch.setenv("RDT_SERVE_HEDGE", "0")
    monkeypatch.setenv("RDT_SERVE_SWAP_DRAIN_S", "1")
    rep = RestartingUnloadReplica("a", refuse=2)
    srv = ServingSession("/bundles/v1", executors=[rep], name="ur")
    try:
        base_failed = metrics.snapshot()["counters"].get(
            "serve_unload_failed_total", {}).get("", 0)
        srv.predict(_rows(1.0), timeout=30.0)
        srv.hot_swap("/bundles/v2")
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and "ur-r0" not in rep.unloaded:
            time.sleep(0.05)
        assert "ur-r0" in rep.unloaded          # landed on the 3rd attempt
        assert rep.unload_attempts["ur-r0"] == 3
        now_failed = metrics.snapshot()["counters"].get(
            "serve_unload_failed_total", {}).get("", 0)
        assert now_failed == base_failed        # retried ≠ leaked
    finally:
        srv.close()


def test_unload_exhaustion_counts_loudly(monkeypatch):
    """A replica that refuses unload through the whole window is a LOUD
    leak: counter + unload_failed event, never silence."""
    from raydp_tpu import metrics
    monkeypatch.setenv("RDT_SERVE_HEDGE", "0")
    monkeypatch.setenv("RDT_SERVE_SWAP_DRAIN_S", "0.5")
    monkeypatch.setenv("RDT_SERVE_REROUTE_GRACE_S", "1")
    rep = RestartingUnloadReplica("a", refuse=10_000)
    srv = ServingSession("/bundles/v1", executors=[rep], name="ulk")
    try:
        base = metrics.snapshot()["counters"].get(
            "serve_unload_failed_total", {}).get("", 0)
        srv.hot_swap("/bundles/v2")
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            now = metrics.snapshot()["counters"].get(
                "serve_unload_failed_total", {}).get("", 0)
            if now > base:
                break
            time.sleep(0.05)
        assert now == base + 1
        ev = [e for e in metrics.events() if e["kind"] == "unload_failed"]
        assert ev and ev[-1]["replica"] == "ulk-r0"
    finally:
        srv.close()
