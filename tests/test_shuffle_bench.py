"""shuffle_bench.py --smoke must keep working (tier-1-safe, tiny data): the
bench harness backing benchmarks/SHUFFLE_BYTES.json cannot rot silently."""

import json
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_shuffle_bench_smoke(tmp_path):
    out_path = tmp_path / "SHUFFLE_BYTES_SMOKE.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               RDT_SHUFFLE_BYTES_PATH=str(out_path))
    env.pop("RDT_ETL_OPTIMIZER", None)
    env.pop("RDT_SHUFFLE_CONSOLIDATE", None)
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "benchmarks", "shuffle_bench.py"),
         "--smoke"],
        env=env, capture_output=True, text=True, timeout=240)
    assert proc.returncode == 0, proc.stderr[-2000:]
    record = json.loads(out_path.read_text())
    assert record["metric"] == "etl_shuffle_bytes" and record["smoke"]
    configs = record["configs"]
    assert set(configs) == {"groupby_low_card", "join_low_card",
                            "groupby_high_card", "join_high_card",
                            "repartition_many"}
    for name, cfg in configs.items():
        assert cfg["identical"], name
        if name != "repartition_many":
            assert 0 < cfg["bytes_opt"] < cfg["bytes_naive"], name
    # the headline: low-cardinality groupby shuffles a small multiple of
    # cardinality rows instead of every input row
    assert configs["groupby_low_card"]["reduction_x"] >= 5.0
    # the control-plane leg: consolidated map outputs + batched metadata must
    # cut store RPCs even at smoke scale (16 maps x 16 buckets)
    many = configs["repartition_many"]
    assert 0 < many["store_rpcs_consolidated"] < many["store_rpcs_naive"]
    assert many["rpc_reduction_x"] >= 3.0
    assert record["all_identical"] is True


def test_shuffle_bench_aqe_smoke(tmp_path):
    """The --aqe leg (benchmarks/AQE.json harness): all three adaptive
    rules off vs on at smoke scale. Structural floors only — the broadcast
    byte drop and the coalesce dispatch drop are deterministic; the skew
    wall uses a loose floor (its seeded per-MB fetch delay dominates, but
    this is a 1-core host under CI load)."""
    out_path = tmp_path / "AQE_SMOKE.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu", RDT_AQE_PATH=str(out_path))
    for k in ("RDT_ETL_AQE", "RDT_AQE_BROADCAST_MAX", "RDT_AQE_SKEW_FACTOR",
              "RDT_AQE_COALESCE_MIN", "RDT_SHUFFLE_CONSOLIDATE",
              "RDT_FAULTS", "RDT_SPECULATION"):
        env.pop(k, None)
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "benchmarks", "shuffle_bench.py"),
         "--aqe", "--smoke"],
        env=env, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-2000:]
    record = json.loads(out_path.read_text())
    assert record["metric"] == "etl_aqe" and record["smoke"]
    assert record["all_identical"] is True
    bc = record["configs"]["broadcast_join"]
    assert bc["identical"], "broadcast changed the join's rows"
    assert bc["aqe_broadcast_on"] >= 1 and bc["aqe_broadcast_off"] == 0
    assert 0 < bc["bytes_on"] < bc["bytes_off"]
    assert bc["reduction_x"] >= 10.0, bc
    sk = record["configs"]["skew_groupby"]
    assert sk["identical"], "skew split changed the groupby's rows"
    assert sk["aqe_split_on"] >= 1 and sk["aqe_split_off"] == 0
    assert sk["speedup_x"] >= 1.2, sk
    co = record["configs"]["coalesce_many"]
    assert co["identical"], "coalescing changed the repartition's rows"
    assert co["reduce_tasks_on"] < co["reduce_tasks_off"]
    assert co["dispatch_reduction_x"] >= 4.0, co


def test_shuffle_bench_pipeline_smoke(tmp_path):
    """The --pipeline leg (benchmarks/PIPELINE.json harness): barrier vs
    pipelined shuffle under the seeded per-map delay spread + per-MiB fetch
    delay. Tier-1-safe floors: overlap must actually be OBSERVED (reducers
    fetched while the map tail ran — the whole mechanism), results
    row-identical, and the no-orphan audit holds with reducers mid-stream;
    the wall speedup is asserted loosely (1-core CI host) — the recorded
    full-size artifact carries the headline number."""
    out_path = tmp_path / "PIPELINE_SMOKE.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               RDT_PIPELINE_PATH=str(out_path))
    for k in ("RDT_FAULTS", "RDT_SPECULATION", "RDT_SHUFFLE_PIPELINE",
              "RDT_ETL_AQE", "RDT_SHUFFLE_CONSOLIDATE"):
        env.pop(k, None)
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "benchmarks", "shuffle_bench.py"),
         "--pipeline", "--smoke"],
        env=env, capture_output=True, text=True, timeout=240)
    assert proc.returncode == 0, proc.stderr[-2000:]
    record = json.loads(out_path.read_text())
    assert record["metric"] == "etl_shuffle_pipeline" and record["smoke"]
    cfg = record["configs"]["pipeline"]
    assert cfg["identical"], "pipelining changed the shuffle's rows"
    assert cfg["pipelined_pipelined"] and not cfg["pipelined_barrier"], cfg
    assert cfg["overlap_s"] > 0, (
        "no reduce-side fetch overlapped the map tail")
    assert cfg["overlap_barrier_s"] == 0.0, cfg
    assert cfg["first_reduce_fetch_s"] is not None \
        and cfg["first_reduce_fetch_s"] < cfg["wall_pipelined_s"], cfg
    assert cfg["orphans_pipelined"] == 0, (
        f"mid-stream reducers orphaned {cfg['orphans_pipelined']} objects")
    assert cfg["orphans_barrier"] == 0, cfg
    assert cfg["speedup_x"] >= 1.1, cfg


def test_shuffle_bench_straggler_smoke(tmp_path):
    """The --straggler leg (benchmarks/STRAGGLER.json harness): a seeded
    one-executor delay, speculation off vs on. At smoke scale the structural
    gap is several-x, so the >=1.5x floor has headroom for host noise; the
    orphan audit pins the won/lost-race contract (every loser blob freed)."""
    out_path = tmp_path / "STRAGGLER_SMOKE.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               RDT_STRAGGLER_PATH=str(out_path))
    for k in ("RDT_FAULTS", "RDT_SPECULATION", "RDT_SPECULATION_QUANTILE",
              "RDT_SPECULATION_MIN_S", "RDT_SPECULATION_MULTIPLIER"):
        env.pop(k, None)
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "benchmarks", "shuffle_bench.py"),
         "--straggler", "--smoke"],
        env=env, capture_output=True, text=True, timeout=240)
    assert proc.returncode == 0, proc.stderr[-2000:]
    record = json.loads(out_path.read_text())
    assert record["metric"] == "etl_straggler_speculation" and record["smoke"]
    cfg = record["configs"]["straggler"]
    assert cfg["identical"], "speculation changed the action's rows"
    assert cfg["speculated_on"] >= 1, cfg
    assert cfg["speculated_off"] == 0, cfg
    assert cfg["orphans_on"] == 0, (
        f"speculation races orphaned {cfg['orphans_on']} store objects")
    assert cfg["speedup_x"] >= 1.5, cfg
