"""Consolidated shuffle fast path: on/off equivalence + control-plane drop.

The contract (mirror of test_etl_optimizer.py's matrix): for EVERY shuffle
flavor, ``RDT_SHUFFLE_CONSOLIDATE=1`` (all buckets of a map task in ONE blob,
read back by byte range) must produce row-for-row identical results to ``=0``
(one blob per bucket), while the stage ledger's ``meta_rpcs`` counter strictly
drops — fewer store control-plane calls is the whole point of the path.
"""

import os

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from raydp_tpu.etl import functions as F
from raydp_tpu.etl import tasks as T
from raydp_tpu.etl.expressions import col
from raydp_tpu.runtime.object_store import ObjectRef, get_client


@pytest.fixture(scope="module")
def session():
    """Module-scoped session: the matrix shares one 2-executor gang."""
    import raydp_tpu

    s = raydp_tpu.init("pytest_consol", num_executors=2, executor_cores=1,
                       executor_memory="512MB")
    yield s
    raydp_tpu.stop()


@pytest.fixture(scope="module")
def wide(session):
    """Integer payloads only, so every flavor compares bit-exact."""
    rng = np.random.RandomState(3)
    n = 2400
    pdf = pd.DataFrame({
        "k": rng.randint(0, 11, n),
        "a": rng.randint(0, 1000, n).astype(np.int64),
        "d": rng.randint(0, 5, n),
        "s": [f"tag{i % 7}" for i in range(n)],
    })
    return session.createDataFrame(pdf, num_partitions=4)


def both_modes(monkeypatch, session, make, sort_cols):
    """Run ``make()`` with consolidation off then on; assert identical
    results; return the per-mode stage reports. Pipelining is pinned OFF:
    this matrix measures the consolidated CONTROL plane, and a pipelined
    stage overlaps map and reduce tasks on one executor, double-counting
    their shared per-process RPC-delta windows — the meta_rpcs
    strictly-drop assertion would turn timing-dependent
    (tests/test_shuffle_pipeline.py owns the pipelined matrix)."""
    outs, reports = {}, {}
    monkeypatch.setenv("RDT_SHUFFLE_PIPELINE", "0")
    for env in ("0", "1"):
        monkeypatch.setenv("RDT_SHUFFLE_CONSOLIDATE", env)
        session.engine.reset_shuffle_stage_report()
        out = make()
        if sort_cols:
            out = out.sort_values(sort_cols).reset_index(drop=True)
        outs[env] = out
        reports[env] = session.engine.shuffle_stage_report()
    monkeypatch.delenv("RDT_SHUFFLE_CONSOLIDATE", raising=False)
    pd.testing.assert_frame_equal(outs["0"], outs["1"])
    # every shuffle stage carries the flag for its mode, and batching +
    # single-seal map outputs strictly shrink the control plane
    assert reports["0"] and reports["1"]
    assert all(not r["consolidated"] for r in reports["0"]), reports["0"]
    assert all(r["consolidated"] for r in reports["1"]), reports["1"]
    meta0 = sum(r["meta_rpcs"] for r in reports["0"])
    meta1 = sum(r["meta_rpcs"] for r in reports["1"])
    assert 0 < meta1 < meta0, (meta0, meta1)
    return outs["1"], reports


# ==== equivalence matrix ===========================================================
def test_groupagg_partial_consolidated(monkeypatch, session, wide):
    out, _ = both_modes(
        monkeypatch, session,
        lambda: wide.groupBy("k").agg(F.sum("a").alias("sa"),
                                      F.count("a").alias("n"),
                                      F.min("d").alias("mn")).to_pandas(),
        ["k"])
    assert len(out) == 11


def test_groupagg_single_phase_consolidated(monkeypatch, session, wide):
    # optimizer off: the naive single-phase shuffle, full rows crossing
    monkeypatch.setenv("RDT_ETL_OPTIMIZER", "0")
    out, reports = both_modes(
        monkeypatch, session,
        lambda: wide.groupBy("k").agg(F.sum("a").alias("sa")).to_pandas(),
        ["k"])
    monkeypatch.delenv("RDT_ETL_OPTIMIZER", raising=False)
    assert [r["stage"] for r in reports["1"]] == ["groupagg"]
    assert len(out) == 11


def test_join_both_sides_consolidated(monkeypatch, session, wide):
    # AQE off: this test pins the BUCKETED shuffle-join's consolidated
    # format (with it on, the tiny dim side broadcasts and neither side
    # shuffles at all — covered by tests/test_aqe.py instead)
    monkeypatch.setenv("RDT_ETL_AQE", "0")
    dim = session.createDataFrame(
        pd.DataFrame({"k": np.arange(11), "label": np.arange(11) * 3}),
        num_partitions=2)
    out, reports = both_modes(
        monkeypatch, session,
        lambda: wide.join(dim, on="k").select("k", "a", "label").to_pandas(),
        ["k", "a"])
    assert {r["stage"] for r in reports["1"]} == {"join-left", "join-right"}
    assert (out["label"] == out["k"] * 3).all()


def test_window_consolidated(monkeypatch, session, wide):
    from raydp_tpu.etl.window import Window

    w = Window.partitionBy("k").orderBy("a")
    out, _ = both_modes(
        monkeypatch, session,
        lambda: (wide.withColumn("rn", F.row_number().over(w))
                 .select("k", "a", "rn").to_pandas()),
        ["k", "a", "rn"])
    assert out["rn"].min() == 1


def test_distinct_consolidated(monkeypatch, session, wide):
    out, _ = both_modes(
        monkeypatch, session,
        lambda: wide.select("k", "d").distinct().to_pandas(),
        ["k", "d"])
    assert len(out) == len(out.drop_duplicates())


def test_repartition_consolidated(monkeypatch, session, wide):
    both_modes(monkeypatch, session,
               lambda: wide.repartition(6).to_pandas(),
               ["k", "a", "d", "s"])


def test_sort_range_consolidated(monkeypatch, session, wide):
    out, reports = both_modes(
        monkeypatch, session,
        lambda: wide.sort("k", ("a", "descending")).to_pandas()
        .reset_index(drop=True),
        None)  # sort output order IS the result; no canonical re-sort
    assert [r["stage"] for r in reports["1"]] == ["sort-range"]
    assert (out["k"].values[:-1] <= out["k"].values[1:]).all()


def test_random_shuffle_consolidated(monkeypatch, session, wide):
    def shuffled():
        eng = session.engine
        refs, schema, _ = eng.materialize(wide._plan)
        client = get_client()
        try:
            out_refs, rows = eng.random_shuffle_refs(refs, schema, seed=7)
            try:
                tables = [client.get(r) for r in out_refs]
                return pa.concat_tables(
                    tables, promote_options="permissive").to_pandas()
            finally:
                client.free(out_refs)
        finally:
            client.free(refs)

    out, reports = both_modes(monkeypatch, session, shuffled,
                              ["k", "a", "d", "s"])
    assert [r["stage"] for r in reports["1"]] == ["random-shuffle"]
    assert len(out) == 2400


def test_string_keys_and_empty_buckets_consolidated(monkeypatch, session,
                                                    wide):
    """String-keyed groupby at low cardinality leaves most buckets empty —
    the consolidated index must round-trip empty bucket streams too."""
    out, _ = both_modes(
        monkeypatch, session,
        lambda: wide.groupBy("s").agg(F.count("a").alias("n")).to_pandas(),
        ["s"])
    assert len(out) == 7 and out["n"].sum() == 2400


def test_consolidated_report_columns(monkeypatch, session, wide):
    """The ledger carries the new control-plane columns on every entry, and
    the consolidated map stage seals ONE blob per map task."""
    monkeypatch.setenv("RDT_SHUFFLE_CONSOLIDATE", "1")
    session.engine.reset_shuffle_stage_report()
    wide.groupBy("k").agg(F.sum("a").alias("sa")).to_pandas()
    report = session.engine.shuffle_stage_report()
    monkeypatch.delenv("RDT_SHUFFLE_CONSOLIDATE", raising=False)
    for entry in report:
        assert {"meta_rpcs", "fetch_rpcs", "consolidated"} <= set(entry)
        assert entry["meta_rpcs"] > 0
        # single-machine pool: every read is a local shm slice, no payload
        # fetch RPC ever fires
        assert entry["fetch_rpcs"] == 0


# ==== unit level ===================================================================
def test_range_ref_source_reads_consolidated_blob():
    """A hand-built consolidated blob (back-to-back IPC streams) decodes
    bucket-exact through RangeRefSource, and the 0-part case shares
    ArrowRefSource's schema fallback."""
    from raydp_tpu.runtime import object_store as os_mod

    srv = os_mod.ObjectStoreServer("sessconsol01")
    cli = os_mod.ObjectStoreClient(srv, "sessconsol01")
    cli._arena_probed = True
    cli._arena = None
    old = os_mod._client
    os_mod.set_client(cli)
    try:
        buckets = [pa.table({"x": list(range(i * 3, i * 3 + 3))})
                   for i in range(3)] + [pa.table({"x": pa.array([], pa.int64())})]
        sink = pa.BufferOutputStream()
        index = []
        for b in buckets:
            start = sink.tell()
            with pa.ipc.new_stream(sink, b.schema) as w:
                w.write_table(b)
            index.append((int(start), int(sink.tell() - start), b.num_rows))
        ref = cli.put_raw(memoryview(sink.getvalue()))
        for b, (off, size, rows) in zip(buckets, index):
            got = T.RangeRefSource([(ref, off, size)]).load()
            assert got.equals(b) and got.num_rows == rows
        # concat across ranges behaves like ArrowRefSource concat
        all_rows = T.RangeRefSource(
            [(ref, off, size) for off, size, _ in index]).load()
        assert all_rows.column("x").to_pylist() == list(range(9))

        schema = buckets[0].schema.serialize().to_pybytes()
        empty_range = T.RangeRefSource([], schema=schema).load()
        empty_arrow = T.ArrowRefSource([], schema=schema).load()
        assert empty_range.equals(empty_arrow)
        with pytest.raises(ValueError):
            T.RangeRefSource([]).load()
    finally:
        os_mod.set_client(old)
        srv.shutdown()


def test_patch_and_input_ids_cover_range_sources():
    """Lineage ref surgery must reach RangeRefSource parts and a join's
    right_parts — offsets survive the swap (reruns are byte-identical)."""
    old = [ObjectRef(id=f"{i:032x}", size=100) for i in range(3)]
    new = ObjectRef(id="f" * 32, size=100)
    task = T.Task(
        task_id="t",
        source=T.RangeRefSource([(old[0], 0, 10), (old[1], 10, 20)]),
        steps=[T.HashJoinStep([], ["k"], ["k"],
                              right_parts=[(old[2], 5, 7)])])
    assert sorted(T.task_input_ids(task)) == sorted(r.id for r in old)

    patched = T.patch_task_refs(task, {old[0].id: new, old[2].id: new})
    assert patched.source.parts[0] == (new, 0, 10)
    assert patched.source.parts[1] == (old[1], 10, 20)
    assert patched.steps[0].right_parts == [(new, 5, 7)]
    # no-match mapping returns the identical task object
    assert T.patch_task_refs(task, {"e" * 32: new}) is task


def test_bucket_source_mixes_legacy_and_consolidated():
    """A stage whose maps disagree on the format (e.g. recovery reran a
    producer under a flipped env) still builds one coherent reader: legacy
    refs normalize to full-blob ranges."""
    from raydp_tpu.etl.engine import Engine

    ref = ObjectRef(id="a" * 32, size=64)
    triple = (ObjectRef(id="b" * 32, size=256), 32, 16)
    src = Engine._bucket_source([ref, triple], None)
    assert isinstance(src, T.RangeRefSource)
    assert src.parts == [(ref, 0, 64), triple]
    legacy = Engine._bucket_source([ref], None)
    assert isinstance(legacy, T.ArrowRefSource) and legacy.refs == [ref]


def test_gather_buckets_transposes_consolidated_results():
    from raydp_tpu.etl.engine import Engine, _ActionTemps

    cref = ObjectRef(id="c" * 32, size=300)
    legacy = [ObjectRef(id=f"{i:031x}d", size=10) for i in range(2)]
    results = [
        {"consolidated_ref": cref,
         "bucket_index": [(0, 100, 5), (100, 200, 7)]},
        {"bucket_refs": legacy},
    ]
    temps = _ActionTemps()
    buckets = Engine._gather_buckets(results, 2, temps)
    assert buckets[0] == [(cref, 0, 100), legacy[0]]
    assert buckets[1] == [(cref, 100, 200), legacy[1]]
    # ONE temp for the consolidated blob, one per legacy bucket
    assert [r.id for r in temps] == [cref.id] + [r.id for r in legacy]
