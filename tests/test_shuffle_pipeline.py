"""Pipelined (push-based) shuffle: on/off equivalence + the AQE rule.

The contract (mirror of test_shuffle_consolidate.py's matrix): for EVERY
shuffle flavor, ``RDT_SHUFFLE_PIPELINE=1`` (reduce tasks dispatched
concurrently with the map stage, consuming seal notifications through
``tasks.StreamingRangeSource``) must produce row-for-row identical results
to ``=0`` (the barrier mode), with the stage ledger's ``pipelined`` flag
marking the mode. The AQE interaction rule is pinned explicitly: **AQE
wins** — a stage AQE may re-plan (groupagg/join/distinct/repartition) runs
in barrier mode whenever ``RDT_ETL_AQE`` is on, while never-re-planned
stages (window, sort-range, random-shuffle) pipeline regardless; and
``RDT_SHUFFLE_CONSOLIDATE=0`` cleanly disables pipelining (the mode needs
the consolidated per-bucket index).
"""

import os

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from raydp_tpu.etl import functions as F
from raydp_tpu.etl import tasks as T
from raydp_tpu.runtime.object_store import ObjectRef, get_client


@pytest.fixture(scope="module")
def session():
    """Module-scoped session: the matrix shares one 2-executor gang."""
    import raydp_tpu

    s = raydp_tpu.init("pytest_pipeline", num_executors=2, executor_cores=1,
                       executor_memory="512MB")
    yield s
    raydp_tpu.stop()


@pytest.fixture(scope="module")
def wide(session):
    """Integer payloads only, so every flavor compares bit-exact."""
    rng = np.random.RandomState(3)
    n = 2400
    pdf = pd.DataFrame({
        "k": rng.randint(0, 11, n),
        "a": rng.randint(0, 1000, n).astype(np.int64),
        "d": rng.randint(0, 5, n),
        "s": [f"tag{i % 7}" for i in range(n)],
    })
    return session.createDataFrame(pdf, num_partitions=4)


def both_modes(monkeypatch, session, make, sort_cols):
    """Run ``make()`` with pipelining off then on (AQE pinned off so the
    AQE-capable flavors actually engage it); assert identical results;
    return the per-mode stage reports."""
    outs, reports = {}, {}
    monkeypatch.setenv("RDT_ETL_AQE", "0")
    for env in ("0", "1"):
        monkeypatch.setenv("RDT_SHUFFLE_PIPELINE", env)
        session.engine.reset_shuffle_stage_report()
        out = make()
        if sort_cols:
            out = out.sort_values(sort_cols).reset_index(drop=True)
        outs[env] = out
        reports[env] = session.engine.shuffle_stage_report()
    monkeypatch.delenv("RDT_SHUFFLE_PIPELINE", raising=False)
    monkeypatch.delenv("RDT_ETL_AQE", raising=False)
    pd.testing.assert_frame_equal(outs["0"], outs["1"])
    assert reports["0"] and reports["1"]
    assert all(not r["pipelined"] for r in reports["0"]), reports["0"]
    assert all(r["pipelined"] for r in reports["1"]), reports["1"]
    return outs["1"], reports


# ==== equivalence matrix ===========================================================
def test_groupagg_partial_pipelined(monkeypatch, session, wide):
    out, _ = both_modes(
        monkeypatch, session,
        lambda: wide.groupBy("k").agg(F.sum("a").alias("sa"),
                                      F.count("a").alias("n"),
                                      F.min("d").alias("mn")).to_pandas(),
        ["k"])
    assert len(out) == 11


def test_groupagg_single_phase_pipelined(monkeypatch, session, wide):
    # optimizer off: the naive single-phase shuffle, full rows crossing
    monkeypatch.setenv("RDT_ETL_OPTIMIZER", "0")
    out, reports = both_modes(
        monkeypatch, session,
        lambda: wide.groupBy("k").agg(F.sum("a").alias("sa")).to_pandas(),
        ["k"])
    monkeypatch.delenv("RDT_ETL_OPTIMIZER", raising=False)
    assert [r["stage"] for r in reports["1"]] == ["groupagg"]
    assert len(out) == 11


def test_join_both_orders_pipelined(monkeypatch, session, wide):
    dim = session.createDataFrame(
        pd.DataFrame({"k": np.arange(11), "label": np.arange(11) * 3}),
        num_partitions=2)
    out, reports = both_modes(
        monkeypatch, session,
        lambda: wide.join(dim, on="k").select("k", "a", "label").to_pandas(),
        ["k", "a"])
    assert {r["stage"] for r in reports["1"]} == {"join-left", "join-right"}
    assert (out["label"] == out["k"] * 3).all()
    # the other order: the streamed side is the BUILD side this time
    out2, _ = both_modes(
        monkeypatch, session,
        lambda: dim.join(wide.select("k", "a"), on="k")
        .select("k", "a", "label").to_pandas(),
        ["k", "a"])
    assert (out2["label"] == out2["k"] * 3).all()


def test_window_pipelined(monkeypatch, session, wide):
    from raydp_tpu.etl.window import Window

    w = Window.partitionBy("k").orderBy("a")
    out, _ = both_modes(
        monkeypatch, session,
        lambda: (wide.withColumn("rn", F.row_number().over(w))
                 .select("k", "a", "rn").to_pandas()),
        ["k", "a", "rn"])
    assert out["rn"].min() == 1


def test_distinct_pipelined(monkeypatch, session, wide):
    out, _ = both_modes(
        monkeypatch, session,
        lambda: wide.select("k", "d").distinct().to_pandas(),
        ["k", "d"])
    assert len(out) == len(out.drop_duplicates())


def test_repartition_pipelined(monkeypatch, session, wide):
    both_modes(monkeypatch, session,
               lambda: wide.repartition(6).to_pandas(),
               ["k", "a", "d", "s"])


def test_sort_range_pipelined(monkeypatch, session, wide):
    out, reports = both_modes(
        monkeypatch, session,
        lambda: wide.sort("k", ("a", "descending")).to_pandas()
        .reset_index(drop=True),
        None)  # sort output order IS the result; no canonical re-sort
    assert [r["stage"] for r in reports["1"]] == ["sort-range"]
    assert (out["k"].values[:-1] <= out["k"].values[1:]).all()


def test_random_shuffle_pipelined(monkeypatch, session, wide):
    def shuffled():
        eng = session.engine
        refs, schema, _ = eng.materialize(wide._plan)
        client = get_client()
        try:
            out_refs, rows = eng.random_shuffle_refs(refs, schema, seed=7)
            try:
                tables = [client.get(r) for r in out_refs]
                return pa.concat_tables(
                    tables, promote_options="permissive").to_pandas()
            finally:
                client.free(out_refs)
        finally:
            client.free(refs)

    out, reports = both_modes(monkeypatch, session, shuffled,
                              ["k", "a", "d", "s"])
    assert [r["stage"] for r in reports["1"]] == ["random-shuffle"]
    assert len(out) == 2400


def test_string_keys_and_empty_buckets_pipelined(monkeypatch, session, wide):
    """String-keyed groupby at low cardinality leaves most buckets empty —
    a streamed read must round-trip empty bucket streams too."""
    out, _ = both_modes(
        monkeypatch, session,
        lambda: wide.groupBy("s").agg(F.count("a").alias("n")).to_pandas(),
        ["s"])
    assert len(out) == 7 and out["n"].sum() == 2400


def test_cascaded_same_label_stages_no_self_wait(monkeypatch, session,
                                                 wide):
    """Regression (review-reproduced): a.join(b).join(c) runs the
    "join-left" label TWICE in one action; the consumed-stream bookkeeping
    must key on the unique stream stage_key, not the label — a label lookup
    handed the outer cascaded map stage its OWN record and its thread
    blocked on a done event only it could set (300 s stall; results were
    correct, just 2000× slower than barrier)."""
    import time

    dim_b = session.createDataFrame(
        pd.DataFrame({"k": np.arange(11), "y": np.arange(11) * 2}),
        num_partitions=2)
    dim_c = session.createDataFrame(
        pd.DataFrame({"k": np.arange(11), "z": np.arange(11) * 3}),
        num_partitions=2)
    t0 = time.monotonic()
    out, reports = both_modes(
        monkeypatch, session,
        lambda: (wide.select("k", "a").join(dim_b, on="k")
                 .join(dim_c, on="k").to_pandas()),
        ["k", "a"])
    assert time.monotonic() - t0 < 60, \
        "cascaded pipelined stages stalled (self-wait regression)"
    assert [r["stage"] for r in reports["1"]].count("join-left") == 2
    assert (out["y"] == out["k"] * 2).all() and \
        (out["z"] == out["k"] * 3).all()


# ==== the pinned interaction rules =================================================
def test_consolidate_off_disables_pipelining(monkeypatch, session, wide):
    """RDT_SHUFFLE_CONSOLIDATE=0 cleanly no-ops pipelining (the mode needs
    the consolidated per-bucket index): results stay correct and the stage
    runs barrier, unpipelined."""
    monkeypatch.setenv("RDT_ETL_AQE", "0")
    monkeypatch.setenv("RDT_SHUFFLE_PIPELINE", "1")
    monkeypatch.setenv("RDT_SHUFFLE_CONSOLIDATE", "0")
    session.engine.reset_shuffle_stage_report()
    out = wide.groupBy("k").agg(F.sum("a").alias("sa")).to_pandas()
    report = session.engine.shuffle_stage_report()
    assert len(out) == 11
    assert report and all(not r["pipelined"] and not r["consolidated"]
                          for r in report), report


def test_aqe_wins_rule_pinned(monkeypatch, session, wide):
    """The documented AQE interaction rule: with RDT_ETL_AQE on (the
    default), stages AQE may re-plan (groupagg/join/distinct/repartition —
    post-map broadcast, skew split, and coalescing need the full map-size
    picture) run BARRIER even with pipelining on; never-re-planned stages
    (window, sort-range, random-shuffle) pipeline regardless."""
    from raydp_tpu.etl.window import Window

    monkeypatch.setenv("RDT_ETL_AQE", "1")
    monkeypatch.setenv("RDT_SHUFFLE_PIPELINE", "1")
    session.engine.reset_shuffle_stage_report()
    wide.groupBy("k").agg(F.sum("a").alias("sa")).to_pandas()
    wide.select("k", "d").distinct().to_pandas()
    wide.sort("k").to_pandas()
    w = Window.partitionBy("k").orderBy("a")
    wide.withColumn("rn", F.row_number().over(w)).select("k", "rn") \
        .to_pandas()
    by_stage = {r["stage"]: r["pipelined"]
                for r in session.engine.shuffle_stage_report()}
    assert by_stage["groupagg-partial"] is False
    assert by_stage["distinct"] is False
    assert by_stage["sort-range"] is True
    assert by_stage["window"] is True


def test_pipelined_report_columns(monkeypatch, session, wide):
    """A pipelined stage's ledger entry carries the overlap columns; a
    barrier stage reports the neutral values."""
    monkeypatch.setenv("RDT_ETL_AQE", "0")
    monkeypatch.setenv("RDT_SHUFFLE_PIPELINE", "1")
    session.engine.reset_shuffle_stage_report()
    wide.repartition(6).to_pandas()
    (entry,) = session.engine.shuffle_stage_report()
    assert entry["pipelined"] is True
    assert entry["overlap_s"] >= 0.0
    assert entry["first_reduce_fetch_s"] is not None \
        and entry["first_reduce_fetch_s"] >= 0.0
    monkeypatch.setenv("RDT_SHUFFLE_PIPELINE", "0")
    session.engine.reset_shuffle_stage_report()
    wide.repartition(6).to_pandas()
    (entry,) = session.engine.shuffle_stage_report()
    assert entry["pipelined"] is False
    assert entry["overlap_s"] == 0.0
    assert entry["first_reduce_fetch_s"] is None


def test_persist_recipes_resolve_streaming_sources(monkeypatch, session,
                                                   wide):
    """cache() recover recipes must NOT bake in streaming sources — the
    seal-stream ledger closes with the action, so a recipe kept in
    streaming form would be permanently unreadable. Proven by wiping every
    executor block cache and reading the frame back through its recipes."""
    from raydp_tpu.runtime import get_runtime

    monkeypatch.setenv("RDT_ETL_AQE", "0")
    monkeypatch.setenv("RDT_SHUFFLE_PIPELINE", "1")
    session.engine.reset_shuffle_stage_report()
    cached = wide.groupBy("k").agg(F.sum("a").alias("sa")).persist()
    try:
        assert any(r["pipelined"]
                   for r in session.engine.shuffle_stage_report())
        # cache()'s success path skips the usual temps free, but the seal
        # streams must still close with the action (an unclosed stage
        # would leak in the head ledger for the session lifetime)
        assert not get_runtime().store_server._streams._stages, \
            "persist() leaked seal-stream ledger entries"
        base = session.engine.collect(cached._plan) \
            .sort_by([("k", "ascending")])
        import cloudpickle
        for blob in cached._plan.recover_tasks:
            task = cloudpickle.loads(blob)
            assert not T.stream_sources_of(task), \
                "recover recipe still holds a streaming source"
        for h in session.executors:
            h.drop_block_prefix("block_")
        got = session.engine.collect(cached._plan) \
            .sort_by([("k", "ascending")])
        assert got.equals(base)
    finally:
        cached.unpersist()


# ==== unit level ===================================================================
def _ledger_server():
    from raydp_tpu.runtime import object_store as os_mod

    srv = os_mod.ObjectStoreServer("sesspipe00001")
    cli = os_mod.ObjectStoreClient(srv, "sesspipe00001")
    cli._arena_probed = True
    cli._arena = None
    return os_mod, srv, cli


def test_streaming_source_orders_by_map_id_not_arrival():
    """Seals arriving out of map order (map 1 before map 0) must still
    concatenate in MAP order — the barrier mode's row order."""
    os_mod, srv, cli = _ledger_server()
    old = os_mod._client
    os_mod.set_client(cli)
    try:
        def consolidated(tbls):
            sink = pa.BufferOutputStream()
            index = []
            for b in tbls:
                start = sink.tell()
                with pa.ipc.new_stream(sink, b.schema) as w:
                    w.write_table(b)
                index.append((int(start), int(sink.tell() - start),
                              b.num_rows))
            return cli.put_raw(memoryview(sink.getvalue())), index

        # two maps × two buckets; publish map 1 FIRST
        r1, i1 = consolidated([pa.table({"x": [10]}), pa.table({"x": [11]})])
        r0, i0 = consolidated([pa.table({"x": [0]}), pa.table({"x": [1]})])
        cli.stream_begin("st1", 2)
        cli.stream_publish("st1", 1, 1, r1.id, r1.size, i1)
        cli.stream_publish("st1", 0, 1, r0.id, r0.size, i0)
        got = T.StreamingRangeSource("st1", bucket=1, num_maps=2).load()
        assert got.column("x").to_pylist() == [1, 11]
        stats = T.StreamingRangeSource("st1", bucket=0, num_maps=2)
        assert stats.load().column("x").to_pylist() == [0, 10]
        assert stats.stream_stats["rounds"] >= 1
    finally:
        os_mod.set_client(old)
        srv.shutdown()


def test_streaming_source_aborts_fast_on_unknown_and_aborted_stage():
    from raydp_tpu.runtime.object_store import ShuffleStreamAborted

    os_mod, srv, cli = _ledger_server()
    old = os_mod._client
    os_mod.set_client(cli)
    try:
        with pytest.raises(ShuffleStreamAborted):
            T.StreamingRangeSource("never-began", 0, 2).load()
        cli.stream_begin("st2", 2)
        cli.stream_abort("st2", "map stage died: boom")
        with pytest.raises(ShuffleStreamAborted, match="boom"):
            T.StreamingRangeSource("st2", 0, 2).load()
        cli.stream_begin("st3", 2)
        cli.stream_close(["st3"])
        with pytest.raises(ShuffleStreamAborted, match="closed"):
            T.StreamingRangeSource("st3", 0, 2).load()
    finally:
        os_mod.set_client(old)
        srv.shutdown()


def test_stream_ledger_long_poll_completes_on_publish_and_timeout():
    """The long-poll half of the metadata plane: a poll with nothing new
    returns a deferred reply, completed by the NEXT publish; an idle poll
    completes empty when its timeout lapses (the lazy sweeper)."""
    import threading
    import time

    from raydp_tpu.runtime.object_store import ObjectStoreServer
    from raydp_tpu.runtime.rpc import DeferredReply

    srv = ObjectStoreServer("sesspipe00002")
    try:
        srv.stream_begin("stA", 1)
        res = srv.stream_poll("stA", 0, {}, timeout_s=30.0)
        assert isinstance(res, DeferredReply)
        assert not res.future.done()
        threading.Timer(0.05, lambda: srv.stream_publish(
            "stA", 0, 1, "a" * 32, 64, [(0, 64, 1)])).start()
        out = res.future.result(timeout=5)
        assert out["events"] == [(0, 1, "a" * 32, 64, 0, 64)]
        assert out["expected"] == 1 and out["aborted"] is None
        # already-known events return immediately (no deferred reply)
        out2 = srv.stream_poll("stA", 0, {}, timeout_s=30.0)
        assert out2["events"] and not isinstance(out2, DeferredReply)
        # nothing newer: the timeout sweeper completes the wait empty
        t0 = time.monotonic()
        res3 = srv.stream_poll("stA", 0, {0: 1}, timeout_s=0.2)
        assert isinstance(res3, DeferredReply)
        out3 = res3.future.result(timeout=5)
        assert out3["events"] == [] and out3["aborted"] is None
        assert time.monotonic() - t0 >= 0.15
    finally:
        srv.shutdown()


def test_stream_ledger_generations_supersede():
    """A re-seal (regenerated producer) under the same map_id with gen+1
    supersedes: a reducer that consumed gen 1 sees gen 2; one that never
    fetched sees only the latest."""
    from raydp_tpu.runtime.object_store import ObjectStoreServer

    srv = ObjectStoreServer("sesspipe00003")
    try:
        srv.stream_begin("stB", 1)
        srv.stream_publish("stB", 0, 1, "a" * 32, 64, [(0, 64, 1)])
        srv.stream_publish("stB", 0, 2, "b" * 32, 64, [(0, 64, 1)])
        out = srv.stream_poll("stB", 0, {}, timeout_s=0)
        assert out["events"] == [(0, 2, "b" * 32, 64, 0, 64)]
        out2 = srv.stream_poll("stB", 0, {0: 1}, timeout_s=0)
        assert out2["events"] == [(0, 2, "b" * 32, 64, 0, 64)]
        out3 = srv.stream_poll("stB", 0, {0: 2}, timeout_s=0)
        assert out3["events"] == []
        # a stale generation arriving late never downgrades the ledger
        srv.stream_publish("stB", 0, 1, "a" * 32, 64, [(0, 64, 1)])
        out4 = srv.stream_poll("stB", 0, {0: 1}, timeout_s=0)
        assert out4["events"] == [(0, 2, "b" * 32, 64, 0, 64)]
    finally:
        srv.shutdown()


def test_streaming_source_keeps_decoded_portion_across_reseal():
    """A re-sealed generation of a portion the reducer ALREADY decoded is
    kept, not refetched (reruns are byte-identical — the test uses
    different bytes purely to observe which copy was used), and the newer
    generation is adopted so the superseded event stops coming back."""
    import threading
    import time as _t

    os_mod, srv, cli = _ledger_server()
    old = os_mod._client
    os_mod.set_client(cli)
    try:
        def consolidated(tbls):
            sink = pa.BufferOutputStream()
            index = []
            for b in tbls:
                start = sink.tell()
                with pa.ipc.new_stream(sink, b.schema) as w:
                    w.write_table(b)
                index.append((int(start), int(sink.tell() - start),
                              b.num_rows))
            return cli.put_raw(memoryview(sink.getvalue())), index

        r0a, i0a = consolidated([pa.table({"x": [1]})])
        r0b, i0b = consolidated([pa.table({"x": [99]})])   # the "re-seal"
        r1, i1 = consolidated([pa.table({"x": [2]})])
        cli.stream_begin("stD", 2)
        cli.stream_publish("stD", 0, 1, r0a.id, r0a.size, i0a)

        out = {}

        def run():
            out["t"] = T.StreamingRangeSource("stD", 0, 2,
                                              poll_timeout_s=2.0).load()

        th = threading.Thread(target=run)
        th.start()
        _t.sleep(0.3)  # let it decode map 0's gen-1 portion
        cli.stream_publish("stD", 0, 2, r0b.id, r0b.size, i0b)  # re-seal
        cli.stream_publish("stD", 1, 1, r1.id, r1.size, i1)
        th.join(timeout=10)
        assert not th.is_alive()
        # map 0's DECODED gen-1 portion was kept; map order preserved
        assert out["t"].column("x").to_pylist() == [1, 2]
    finally:
        os_mod.set_client(old)
        srv.shutdown()


def test_resolve_stream_sources_rewrites_to_ranges():
    ref = ObjectRef(id="c" * 32, size=128)

    def resolver(stage_key, bucket):
        assert stage_key == "stC"
        return [(ref, bucket * 10, 10)]

    task = T.Task(
        task_id="t",
        source=T.StreamingRangeSource("stC", 2, 3),
        steps=[T.HashJoinStep([], ["k"], ["k"],
                              right_stream=T.StreamingRangeSource(
                                  "stC", 1, 3))])
    out = T.resolve_stream_sources(task, resolver)
    assert isinstance(out.source, T.RangeRefSource)
    assert out.source.parts == [(ref, 20, 10)]
    assert out.steps[0].right_stream is None
    assert out.steps[0].right_parts == [(ref, 10, 10)]
    assert not T.stream_sources_of(out)
    # a task with no streaming sources returns identity
    plain = T.Task(task_id="p", source=T.ArrowRefSource([ref]))
    assert T.resolve_stream_sources(plain, resolver) is plain
