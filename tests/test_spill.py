"""Object-store eviction/spill: LRU to disk under an shm budget.

Parity: plasma evicts/spills objects under memory pressure instead of failing
or sprawling shared memory (SURVEY.md §2.3 item 11). Here sealed head-host
objects past the configured shm budget spill to the session spill dir and
fault back into shared memory transparently on read — writing 2× the budget
and reading every byte back must work with bounded shm accounting.
"""

import os

import numpy as np
import pytest

from raydp_tpu import config as cfg
from raydp_tpu.config import Config
from raydp_tpu.runtime.head import RuntimeContext

BUDGET = 2 << 20  # 2 MiB
OBJ = 400_000     # ~0.4 MiB each


@pytest.fixture
def spill_rt():
    rt = RuntimeContext(config=Config({
        cfg.OBJECT_STORE_MEMORY_KEY: str(BUDGET),
        cfg.SPILL_BUDGET_KEY: str(BUDGET),
    }))
    # immediate arena reclamation so spilled arena bytes free right away
    rt.store_server.host.ARENA_FREE_GRACE_S = 0.0
    yield rt
    rt.shutdown()


def test_write_2x_budget_read_all_back(spill_rt):
    rt = spill_rt
    client = rt.store_client
    payloads = []
    for i in range(10):  # 10 × 0.4 MiB = 2× the 2 MiB budget
        data = np.random.RandomState(i).bytes(OBJ)
        payloads.append((client.put_raw(data), data))

    stats = rt.store_server.stats()
    assert stats["spilled_objects"] > 0, "nothing spilled past the budget"
    assert stats["shm_bytes"] <= BUDGET + OBJ, stats
    assert stats["spilled_bytes"] + stats["shm_bytes"] == 10 * OBJ
    spill_dir = rt.store_server.spill_dir
    assert spill_dir and os.path.isdir(spill_dir)
    assert len(os.listdir(spill_dir)) == stats["spilled_objects"]

    # every object reads back byte-identical (transparent fault-in), and the
    # budget still holds afterwards — reads must not inflate shm unboundedly
    for ref, data in payloads:
        assert client.get(ref) == data
    after = rt.store_server.stats()
    assert after["shm_bytes"] <= BUDGET + OBJ, after
    assert after["spilled_bytes"] + after["shm_bytes"] == 10 * OBJ


def test_free_removes_spill_files(spill_rt):
    rt = spill_rt
    client = rt.store_client
    refs = [client.put_raw(np.random.RandomState(i).bytes(OBJ))
            for i in range(10)]
    spill_dir = rt.store_server.spill_dir
    assert len(os.listdir(spill_dir)) > 0
    client.free(refs)
    assert rt.store_server.stats()["num_objects"] == 0
    assert os.listdir(spill_dir) == []
    assert rt.store_server.stats()["shm_bytes"] == 0
    assert rt.store_server.stats()["spilled_bytes"] == 0


def test_lru_order_spills_coldest_first(spill_rt):
    rt = spill_rt
    client = rt.store_client
    refs = [client.put_raw(np.random.RandomState(i).bytes(OBJ))
            for i in range(5)]  # fits: 2.0 MiB of 2 MiB budget... borderline
    # touch ref 0 so it is the HOTTEST, then overflow the budget
    client.get(refs[0])
    overflow = [client.put_raw(np.random.RandomState(100 + i).bytes(OBJ))
                for i in range(4)]
    server = rt.store_server
    # ref 0 was recently read: colder refs must have spilled before it
    _, _, _, _, _, _ = server.lookup(refs[0].id)
    with server._lock:
        spilled = {oid for oid, e in server._table.items() if e.spilled}
    cold_ids = {r.id for r in refs[1:]}
    assert spilled & cold_ids, "no cold object spilled"
    for ref in refs + overflow:
        assert client.contains(ref)


def test_spill_disabled_with_zero_budget():
    rt = RuntimeContext(config=Config({
        cfg.OBJECT_STORE_MEMORY_KEY: str(BUDGET),
        cfg.SPILL_BUDGET_KEY: "0",
    }))
    try:
        client = rt.store_client
        refs = [client.put_raw(np.random.RandomState(i).bytes(OBJ))
                for i in range(10)]
        assert rt.store_server.spill_dir is None
        assert rt.store_server.stats()["spilled_objects"] == 0
        for i, ref in enumerate(refs):
            assert client.get(ref) == np.random.RandomState(i).bytes(OBJ)
    finally:
        rt.shutdown()


# ==== stage-aware eviction + AQE-fed budgets (ISSUE 19) ======================


def test_eviction_hints_order_bands(spill_rt):
    """Victim order is (hint band, LRU): evict-first blobs (consumer stage
    done) spill before unhinted ones; blobs pinned by a running stage go
    last; LRU breaks ties only inside a band."""
    rt = spill_rt
    client = rt.store_client
    server = rt.store_server
    refs = [client.put_raw(np.random.RandomState(i).bytes(OBJ))
            for i in range(5)]  # 2.0 MB of the 2 MiB budget: nothing spills
    client.eviction_hints(pin=[refs[0], refs[1]], evict_first=[refs[4]])
    # overflow by two objects: exactly two victims must spill
    client.put_raw(np.random.RandomState(100).bytes(OBJ))
    client.put_raw(np.random.RandomState(101).bytes(OBJ))
    with server._lock:
        spilled = {oid for oid, e in server._table.items() if e.spilled}
    assert refs[4].id in spilled, "evict-first blob outlived the overflow"
    assert refs[0].id not in spilled and refs[1].id not in spilled, \
        "a pinned blob spilled while unpinned candidates remained"
    # the second victim is the LRU of the unhinted band (refs[2] < refs[3])
    assert refs[2].id in spilled and refs[3].id not in spilled
    # unpin at refcount zero demotes to evict-first: the released blobs
    # become the next victims, ahead of the (newer) unhinted overflow blobs
    client.eviction_hints(unpin=[refs[0], refs[1]])
    client.put_raw(np.random.RandomState(102).bytes(OBJ))
    with server._lock:
        spilled2 = {oid for oid, e in server._table.items() if e.spilled}
    assert refs[0].id in spilled2, "released pin was not evicted first"
    assert refs[3].id not in spilled2


def test_pin_refcounts_shared_inputs(spill_rt):
    """Two concurrent stages pinning the same blob: one stage finishing
    (one unpin) must NOT demote it while the other still reads it."""
    rt = spill_rt
    client = rt.store_client
    server = rt.store_server
    ref = client.put_raw(b"x" * 1000)
    client.eviction_hints(pin=[ref])
    client.eviction_hints(pin=[ref])        # second stage shares the input
    client.eviction_hints(unpin=[ref])      # first stage completes
    stats = server.stats()
    assert stats["pinned_objects"] == 1, "shared pin dropped too early"
    assert stats["evict_first_objects"] == 0
    client.eviction_hints(unpin=[ref])      # second stage completes
    stats = server.stats()
    assert stats["pinned_objects"] == 0
    assert stats["evict_first_objects"] == 1


def test_pinned_blobs_still_spill_as_last_resort(spill_rt):
    """The budget invariant outranks every hint: with ALL blobs pinned, an
    overflow still spills (pinned band last) and shm stays bounded."""
    rt = spill_rt
    client = rt.store_client
    refs = [client.put_raw(np.random.RandomState(i).bytes(OBJ))
            for i in range(5)]
    client.eviction_hints(pin=refs)
    for i in range(4):
        client.put_raw(np.random.RandomState(200 + i).bytes(OBJ))
    stats = rt.store_server.stats()
    assert stats["shm_bytes"] <= BUDGET + OBJ, \
        "pinning broke the bounded-shm contract"
    assert stats["spilled_objects"] > 0
    # everything still reads back (transparent fault-in)
    for i, ref in enumerate(refs):
        assert client.get(ref) == np.random.RandomState(i).bytes(OBJ)


def test_derive_budgets_tightens_never_widens(spill_rt):
    """AQE-fed budgets: derived = min(static, measured x headroom). A small
    measured working set tightens the budget (cold bytes spill ahead of
    demand); a huge one leaves the static capacity standing."""
    rt = spill_rt
    client = rt.store_client
    server = rt.store_server
    for i in range(4):  # 1.6 MB: under the 2 MiB static budget, all shm
        client.put_raw(np.random.RandomState(i).bytes(OBJ))
    assert server.stats()["spilled_objects"] == 0
    # measured 400 KB x 1.5 headroom = 600 KB -> floored to 1 MiB: spills
    # the cold tail down to the derived budget
    derived = client.derive_budgets(400_000)
    from raydp_tpu.runtime.object_store import HEAD_HOST
    assert derived[HEAD_HOST] == 1 << 20
    stats = server.stats()
    assert stats["derived_budgets"] == {HEAD_HOST: 1 << 20}
    assert stats["shm_bytes"] <= (1 << 20), \
        "tightened budget did not spill ahead of demand"
    assert stats["spilled_objects"] >= 2
    # a measured set far past capacity: the static number stands
    derived = client.derive_budgets(100 << 20)
    assert derived[HEAD_HOST] == BUDGET
