"""Object-store eviction/spill: LRU to disk under an shm budget.

Parity: plasma evicts/spills objects under memory pressure instead of failing
or sprawling shared memory (SURVEY.md §2.3 item 11). Here sealed head-host
objects past the configured shm budget spill to the session spill dir and
fault back into shared memory transparently on read — writing 2× the budget
and reading every byte back must work with bounded shm accounting.
"""

import os

import numpy as np
import pytest

from raydp_tpu import config as cfg
from raydp_tpu.config import Config
from raydp_tpu.runtime.head import RuntimeContext

BUDGET = 2 << 20  # 2 MiB
OBJ = 400_000     # ~0.4 MiB each


@pytest.fixture
def spill_rt():
    rt = RuntimeContext(config=Config({
        cfg.OBJECT_STORE_MEMORY_KEY: str(BUDGET),
        cfg.SPILL_BUDGET_KEY: str(BUDGET),
    }))
    # immediate arena reclamation so spilled arena bytes free right away
    rt.store_server.host.ARENA_FREE_GRACE_S = 0.0
    yield rt
    rt.shutdown()


def test_write_2x_budget_read_all_back(spill_rt):
    rt = spill_rt
    client = rt.store_client
    payloads = []
    for i in range(10):  # 10 × 0.4 MiB = 2× the 2 MiB budget
        data = np.random.RandomState(i).bytes(OBJ)
        payloads.append((client.put_raw(data), data))

    stats = rt.store_server.stats()
    assert stats["spilled_objects"] > 0, "nothing spilled past the budget"
    assert stats["shm_bytes"] <= BUDGET + OBJ, stats
    assert stats["spilled_bytes"] + stats["shm_bytes"] == 10 * OBJ
    spill_dir = rt.store_server.spill_dir
    assert spill_dir and os.path.isdir(spill_dir)
    assert len(os.listdir(spill_dir)) == stats["spilled_objects"]

    # every object reads back byte-identical (transparent fault-in), and the
    # budget still holds afterwards — reads must not inflate shm unboundedly
    for ref, data in payloads:
        assert client.get(ref) == data
    after = rt.store_server.stats()
    assert after["shm_bytes"] <= BUDGET + OBJ, after
    assert after["spilled_bytes"] + after["shm_bytes"] == 10 * OBJ


def test_free_removes_spill_files(spill_rt):
    rt = spill_rt
    client = rt.store_client
    refs = [client.put_raw(np.random.RandomState(i).bytes(OBJ))
            for i in range(10)]
    spill_dir = rt.store_server.spill_dir
    assert len(os.listdir(spill_dir)) > 0
    client.free(refs)
    assert rt.store_server.stats()["num_objects"] == 0
    assert os.listdir(spill_dir) == []
    assert rt.store_server.stats()["shm_bytes"] == 0
    assert rt.store_server.stats()["spilled_bytes"] == 0


def test_lru_order_spills_coldest_first(spill_rt):
    rt = spill_rt
    client = rt.store_client
    refs = [client.put_raw(np.random.RandomState(i).bytes(OBJ))
            for i in range(5)]  # fits: 2.0 MiB of 2 MiB budget... borderline
    # touch ref 0 so it is the HOTTEST, then overflow the budget
    client.get(refs[0])
    overflow = [client.put_raw(np.random.RandomState(100 + i).bytes(OBJ))
                for i in range(4)]
    server = rt.store_server
    # ref 0 was recently read: colder refs must have spilled before it
    _, _, _, _, _, _ = server.lookup(refs[0].id)
    with server._lock:
        spilled = {oid for oid, e in server._table.items() if e.spilled}
    cold_ids = {r.id for r in refs[1:]}
    assert spilled & cold_ids, "no cold object spilled"
    for ref in refs + overflow:
        assert client.contains(ref)


def test_spill_disabled_with_zero_budget():
    rt = RuntimeContext(config=Config({
        cfg.OBJECT_STORE_MEMORY_KEY: str(BUDGET),
        cfg.SPILL_BUDGET_KEY: "0",
    }))
    try:
        client = rt.store_client
        refs = [client.put_raw(np.random.RandomState(i).bytes(OBJ))
                for i in range(10)]
        assert rt.store_server.spill_dir is None
        assert rt.store_server.stats()["spilled_objects"] == 0
        for i, ref in enumerate(refs):
            assert client.get(ref) == np.random.RandomState(i).bytes(OBJ)
    finally:
        rt.shutdown()
