"""Gang-SPMD job runner tests (parity model: reference test_mpi.py — start/run/
stop + restart of the same job object, rank addressing, env propagation,
placement-group variant; SURVEY.md §4)."""

import os

import numpy as np
import pytest

from raydp_tpu.spmd import create_spmd_job


def test_start_run_stop_restart():
    job = create_spmd_job("t-basic", world_size=3, timeout=60)
    job.start()
    try:
        results = job.run(lambda ctx: ctx.rank * 10)
        assert results == [0, 10, 20]
        # in-order sequencing: a second broadcast works
        results = job.run(lambda ctx: ctx.world_size)
        assert results == [3, 3, 3]
    finally:
        job.stop()
    # the same object restarts cleanly (parity: test_mpi.py restart case)
    job.start()
    try:
        assert job.run(lambda ctx: ctx.job_id) == ["t-basic"] * 3
    finally:
        job.stop()


def test_env_propagation():
    job = create_spmd_job("t-env", world_size=2,
                          env={"RDT_TEST_MARKER": "hello"}, timeout=60)
    job.start()
    try:
        # rdtlint: allow[knob-registry] probes extra_env propagation, not a knob
        got = job.run(lambda ctx: os.environ.get("RDT_TEST_MARKER"))
        assert got == ["hello", "hello"]
    finally:
        job.stop()


def test_rank_addresses():
    job = create_spmd_job("t-addr", world_size=2, timeout=60)
    job.start()
    try:
        addrs = job.rank_addresses()
        assert set(addrs) == {0, 1}
        assert all(len(a) == 2 for a in addrs.values())
    finally:
        job.stop()


def test_failure_surfaces_rank_and_traceback():
    job = create_spmd_job("t-fail", world_size=2, timeout=60)
    job.start()
    try:
        def boom(ctx):
            if ctx.rank == 1:
                raise ValueError("rank 1 exploded")
            return "ok"

        with pytest.raises(RuntimeError, match="rank 1"):
            job.run(boom)
        # the gang survives a function failure and keeps sequencing
        assert job.run(lambda ctx: ctx.rank) == [0, 1]
    finally:
        job.stop()


def test_placement_group_accounting(runtime):
    job = create_spmd_job("t-pg", world_size=2, cpus_per_process=1.0, timeout=60)
    job.start()
    try:
        assert job._placement_group_id is not None
        assert runtime.resource_manager.get_group(job._placement_group_id) is not None
    finally:
        job.stop()
    # pg removed on stop (parity: pg-leak check, test_spark_cluster.py:219-259)
    assert runtime.resource_manager.get_group("t-pg") is None


def test_ranks_share_object_store(runtime):
    """Ranks inherit the head env and can exchange data through the store —
    parity with every MPI rank joining Ray (mpi_worker.py:159-160)."""
    import pyarrow as pa

    table = pa.table({"x": np.arange(64, dtype=np.int64)})
    ref = runtime.store_client.put(table)

    job = create_spmd_job("t-store", world_size=2, timeout=60)
    job.start()
    try:
        def read_sum(ctx, ref=ref):
            from raydp_tpu.runtime.object_store import get_client
            t = get_client().get(ref)
            return int(np.asarray(t["x"]).sum())

        assert job.run(read_sum) == [2016, 2016]
    finally:
        job.stop()


def test_stop_escalation_sigkills_straggler_and_job_restarts():
    """Gang teardown robustness (parity: the reference's test_mpi restart
    case, mpi_job.py:344-395): (1) a rank SIGKILLed mid-life must not wedge
    ``stop()`` or the next ``start()``; (2) a rank that ignores the stop RPC
    (simulated with SIGSTOP) is SIGKILLed by the 5s escalation poll; (3) the
    same job object runs a full start→run→stop cycle after each."""
    import signal
    import time

    job = create_spmd_job("t-killrank", world_size=2, timeout=60)

    # cycle 1: kill a rank outright, then stop + restart
    job.start()
    try:
        assert job.run(lambda ctx: ctx.rank) == [0, 1]
        victim = job._procs[0]
        os.killpg(victim.pid, signal.SIGKILL)
        deadline = time.time() + 10
        while victim.poll() is None and time.time() < deadline:
            time.sleep(0.05)
        assert victim.poll() is not None
    finally:
        job.stop()

    # cycle 2: restart works after rank death; then wedge a rank so the stop
    # RPC is never processed — the escalation must SIGKILL it within ~5s
    job.start()
    try:
        assert job.run(lambda ctx: ctx.rank * 2) == [0, 2]
        straggler = job._procs[1]
        os.kill(straggler.pid, signal.SIGSTOP)
    finally:
        t0 = time.time()
        job.stop()
        elapsed = time.time() - t0
    assert elapsed < 30, f"stop() took {elapsed:.1f}s against a straggler"
    deadline = time.time() + 10
    while straggler.poll() is None and time.time() < deadline:
        time.sleep(0.05)
    assert straggler.poll() is not None, "straggler survived stop()"

    # cycle 3: the object still restarts cleanly after the escalated stop
    job.start()
    try:
        assert job.run(lambda ctx: ctx.job_id) == ["t-killrank"] * 2
    finally:
        job.stop()


def test_jax_distributed_gang():
    """world=2 ranks form one jax.distributed mesh; a psum across the global
    device set returns the world sum on every rank — the XLA-collective
    replacement for the reference's in-rank MPI allreduce."""
    job = create_spmd_job(
        "t-jaxdist", world_size=2, jax_distributed=True, timeout=180,
        env={"XLA_FLAGS": "--xla_force_host_platform_device_count=1",
             "JAX_PLATFORMS": "cpu"})
    job.start()
    try:
        def allreduce(ctx):
            import jax
            import jax.numpy as jnp
            from jax.sharding import Mesh, PartitionSpec as P
            from jax.experimental.shard_map import shard_map

            devices = np.array(jax.devices())
            assert devices.size == ctx.world_size
            mesh = Mesh(devices, ("dp",))

            def f(x):
                return jax.lax.psum(x, "dp")

            shard = jnp.array([float(ctx.rank + 1)])
            out = jax.jit(shard_map(
                f, mesh=mesh, in_specs=P("dp"), out_specs=P()))(
                    jax.make_array_from_process_local_data(
                        jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec("dp")),
                        shard, (ctx.world_size,)))
            return float(np.asarray(out)[0])

        assert job.run(allreduce, timeout=180) == [3.0, 3.0]
    finally:
        job.stop()


def test_gang_ring_attention_across_processes():
    """Sequence parallelism spanning PROCESS boundaries: a 2-process gang
    forms one global mesh with a 16-way seq axis; ring attention rotates K/V
    blocks through cross-process collectives and must match a locally
    computed dense reference on every rank (the long-context pillar running
    the way a TPU pod runs it — one process per host)."""
    from raydp_tpu.spmd import create_spmd_job

    def fn(ctx):
        import jax
        import numpy as np

        from jax.sharding import NamedSharding, PartitionSpec as P
        from raydp_tpu.ops.ring_attention import (
            dense_attention, ring_attention_sharded)
        from raydp_tpu.parallel import MeshSpec, make_mesh

        n = jax.device_count()
        mesh = make_mesh(MeshSpec(seq=n))
        B, T, H, D = 1, 16 * n, 2, 8
        rng = np.random.RandomState(0)   # same data on every rank
        q, k, v = (rng.randn(B, T, H, D).astype(np.float32) for _ in range(3))

        sh = NamedSharding(mesh, P(None, "seq"))
        rows = T // ctx.world_size
        lo = ctx.rank * rows
        qg, kg, vg = (jax.make_array_from_process_local_data(
            sh, a[:, lo:lo + rows]) for a in (q, k, v))

        with mesh:
            out = ring_attention_sharded(qg, kg, vg, mesh, causal=True)
        ref = np.asarray(dense_attention(*map(jax.numpy.asarray, (q, k, v)),
                                         causal=True))
        worst = 0.0
        for shard in out.addressable_shards:
            t0 = shard.index[1].start or 0
            got = np.asarray(shard.data)
            want = ref[:, t0:t0 + got.shape[1]]
            worst = max(worst, float(np.max(np.abs(got - want))))
        return worst

    job = create_spmd_job("ring-gang", world_size=2, jax_distributed=True,
                          timeout=180.0)
    job.start()
    try:
        errors = job.run(fn, timeout=600.0)
    finally:
        job.stop()
    assert len(errors) == 2
    assert all(e < 2e-5 for e in errors), errors
