"""Batched metadata plane + client caching units (ISSUE 4 runtime layer):
seal_batch/lookup_batch/put_raw_many, the client lookup memo, ranged reads,
the attached-segment handle-leak regression, and close() teardown."""

import threading

import pyarrow as pa
import pytest

from raydp_tpu.runtime.object_store import (
    KIND_RAW, ObjectLostError, ObjectRef, ObjectStoreClient,
    ObjectStoreServer,
)


@pytest.fixture
def store():
    srv = ObjectStoreServer("sessbatch0001")
    cli = ObjectStoreClient(srv, "sessbatch0001")
    # force the per-object-segment path: that is where the memo applies and
    # where the handle leak lived
    cli._arena_probed = True
    cli._arena = None
    yield srv, cli
    cli.close()
    srv.shutdown()


# ==== server: batched table ops ====================================================
def test_seal_batch_is_one_op_and_atomic(store):
    srv, cli = store
    refs = cli.put_raw_many([(b"aa", KIND_RAW), (b"bbb", KIND_RAW),
                             (b"", KIND_RAW)])
    assert [r.size for r in refs] == [2, 3, 0]
    counts = srv.op_counts()
    assert counts.get("seal_batch") == 1 and "seal" not in counts
    assert [cli.get(r) for r in refs] == [b"aa", b"bbb", b""]

    # duplicate id rejects the WHOLE batch before anything lands
    spec = (refs[0].id, "seg", 1, KIND_RAW, "o", -1)
    fresh = ("9" * 32, "seg9", 1, KIND_RAW, "o", -1)
    with pytest.raises(KeyError):
        srv.seal_batch([fresh, spec])
    assert not srv.contains("9" * 32)


def test_lookup_batch_one_op_missing_ids_absent(store):
    srv, cli = store
    refs = cli.put_raw_many([(b"x", KIND_RAW), (b"y", KIND_RAW)])
    srv.reset_op_counts()
    out = srv.lookup_batch([refs[0].id, "0" * 32, refs[1].id])
    assert set(out) == {refs[0].id, refs[1].id}
    assert srv.op_counts() == {"lookup_batch": 1}


def test_put_raw_many_rolls_back_payloads_on_seal_failure(store):
    srv, cli = store

    class _Boom:
        def __getattr__(self, item):
            return getattr(srv, item)

        def seal_batch(self, specs):
            self.specs = specs
            raise RuntimeError("table down")

    boom = _Boom()
    cli._server = boom
    try:
        with pytest.raises(RuntimeError):
            cli.put_raw_many([(b"zz", KIND_RAW)])
    finally:
        cli._server = srv
    # the written segment was unlinked, not leaked until session end
    from multiprocessing import shared_memory
    seg = boom.specs[0][1]
    with pytest.raises(FileNotFoundError):
        shared_memory.SharedMemory(name=seg)


# ==== client: lookup memo ==========================================================
def test_lookup_memo_hits_cost_no_rpc_and_refresh_bypasses(store):
    srv, cli = store
    refs = cli.put_raw_many([(b"m0", KIND_RAW), (b"m1", KIND_RAW)])
    ids = [r.id for r in refs]
    cli.lookup_many(ids)
    m0 = cli.meta_rpc_count
    assert set(cli.lookup_many(ids)) == set(ids)
    assert cli.get(refs[0]) == b"m0"
    assert cli.meta_rpc_count == m0, "memo hit still paid an RPC"
    srv.reset_op_counts()
    cli.lookup_many(ids, fresh=True)
    assert srv.op_counts() == {"lookup_batch": 1}


def test_memo_never_caches_arena_resident_entries(store):
    srv, cli = store
    # an arena-resident entry (offset >= 0) must not be memoized: the arena
    # segment name never changes, so a recycled offset would be read silently
    cli._memoize("a" * 32, ("arena_seg", 10, KIND_RAW, 128, "head", None))
    cli._memoize("b" * 32, ("dedicated", 10, KIND_RAW, -1, "head", None))
    assert "a" * 32 not in cli._lookup_memo
    assert "b" * 32 in cli._lookup_memo


def test_fresh_process_sees_loss_after_free(store):
    """A reader with no cached state must surface ObjectLostError for a blob
    freed elsewhere — the typed signal lineage recovery keys on."""
    srv, cli = store
    ref = cli.put_raw(b"gone", KIND_RAW)
    srv.free([ref.id])
    with pytest.raises(ObjectLostError):
        cli.get(ref)
    assert ref.id not in cli._lookup_memo and ref.id not in cli._seg_of


# ==== client: ranged reads =========================================================
def test_get_range_buffers_local_slices_and_bounds(store):
    srv, cli = store
    refs = cli.put_raw_many([(b"0123456789", KIND_RAW),
                             (b"abcdef", KIND_RAW)])
    m0 = srv.op_counts().get("lookup", 0)
    bufs = cli.get_range_buffers([(refs[0], 2, 4), (refs[1], 0, 3),
                                  (refs[0], 0, 10)])
    assert bufs == [b"2345", b"abc", b"0123456789"]
    # resolution rode lookup_batch, never per-ref lookup
    assert srv.op_counts().get("lookup", 0) == m0
    with pytest.raises(ValueError):
        cli.get_range_buffers([(refs[1], 4, 10)])


def test_get_range_buffers_lost_blob_raises_typed(store):
    srv, cli = store
    ref = cli.put_raw(b"payload", KIND_RAW)
    srv.free([ref.id])
    with pytest.raises(ObjectLostError):
        cli.get_range_buffers([(ref, 0, 3)])


# ==== client: handle-leak regression (ISSUE 4 satellite) ===========================
def test_attached_handles_released_on_free_cycle(store):
    """put → get → free on the per-segment (arena-full) path returns the
    attached-handle count to baseline; the old code cached SharedMemory
    handles per segment and never evicted."""
    srv, cli = store
    base = len(cli._attached)
    refs = cli.put_raw_many([(b"h%d" % i, KIND_RAW) for i in range(8)])
    for r in refs:
        assert cli.get(r).startswith(b"h")
    assert len(cli._attached) == base + 8
    cli.free(refs)
    assert len(cli._attached) == base
    assert not cli._seg_of and not cli._lookup_memo


def test_view_pinned_handle_retires_then_sweeps(store):
    srv, cli = store
    ref = cli.put_raw(b"pinned", KIND_RAW)
    view = cli.get_buffer(ref)
    cli.free([ref])
    # the mapping is still pinned by the borrowed view: retired, not leaked
    assert len(cli._attached) == 0 and len(cli._retired) == 1
    del view
    cli._sweep_retired()
    assert len(cli._retired) == 0


def test_lost_object_evicts_stale_handle(store):
    srv, cli = store
    ref = cli.put_raw(b"stale", KIND_RAW)
    assert cli.get(ref) == b"stale"
    assert len(cli._seg_of) == 1
    # free behind the client's back, then drop its caches as a loss would
    srv.free([ref.id])
    cli._evict(ref.id)
    assert not cli._seg_of and not cli._attached


def test_remote_mode_range_read_translates_loss(store):
    """The shm-less compat path of get_range_buffers must surface a freed
    blob as the typed ObjectLostError — a bare KeyError is in the engine's
    no-retry set and would fail the stage instead of entering lineage
    recovery (review finding)."""
    srv, _ = store
    cli = ObjectStoreClient(srv, "sessbatch0001", remote=True)
    ref = cli.put_raw(b"remote-blob", KIND_RAW)
    assert cli.get_range_buffers([(ref, 2, 4)]) == [b"mote"]
    srv.free([ref.id])
    with pytest.raises(ObjectLostError):
        cli.get_range_buffers([(ref, 0, 3)])


def test_remote_fetch_ranges_one_rpc_per_peer_and_both_layouts():
    """Ranged reads of payloads on ANOTHER machine ride ONE
    store_fetch_ranges RPC per peer host, and the wire format keeps the
    payload's table offset (base) separate from the range offset — folding
    them into one absolute offset would make a positive value look
    arena-resident to the payload host (the regression this test pins for
    dedicated-segment blobs)."""
    from raydp_tpu.runtime.object_store import PayloadHost
    from raydp_tpu.runtime.rpc import MethodDispatcher, RpcServer

    payload_host = PayloadHost(None)  # dedicated-segment layout (no arena)

    class _Agent:
        def store_fetch_ranges(self, items):
            return [payload_host.fetch_range(s, int(b), int(o), int(z))
                    for s, b, o, z in items]

    server = RpcServer(MethodDispatcher(_Agent()), port=0, name="agent")
    addr = f"{server.address[0]}:{server.address[1]}"
    srv = ObjectStoreServer("sessranges001")
    cli = ObjectStoreClient(srv, "sessranges001", host_id="head")
    cli._arena_probed = True
    cli._arena = None
    try:
        seg, off = payload_host.write(b"0123456789abcdef",
                                      "rdtsessrang_blob1")
        assert off == -1  # dedicated segment: the layout that regressed
        srv.seal("a" * 32, seg, 16, KIND_RAW, "o", off, "node-a", addr)
        ref = ObjectRef(id="a" * 32, size=16)
        bufs = cli.get_range_buffers([(ref, 2, 4), (ref, 10, 6)])
        assert bufs == [b"2345", b"abcdef"]
        assert cli.fetch_rpc_count == 1, "ranges did not batch into one RPC"

        # head-hosted payload read from a node machine goes through the
        # table server's fetch_ranges (the head IS that payload's host)
        seg2, off2 = srv.host.write(b"headbytesxyz", "rdtsessrang_blob2")
        srv.seal("b" * 32, seg2, 12, KIND_RAW, "o", off2, "head", None)
        node_cli = ObjectStoreClient(srv, "sessranges001", host_id="node-b")
        node_cli._arena_probed = True
        node_cli._arena = None
        ref2 = ObjectRef(id="b" * 32, size=12)
        assert node_cli.get_range_buffers([(ref2, 4, 5)]) == [b"bytes"]
        assert srv.op_counts().get("fetch_ranges") == 1

        # dead peer: the typed loss signal, so lineage recovery can key on it
        server.stop()
        lost_cli = ObjectStoreClient(srv, "sessranges001", host_id="head")
        lost_cli._arena_probed = True
        lost_cli._arena = None
        with pytest.raises(ObjectLostError):
            lost_cli.get_range_buffers([(ref, 0, 4)])
    finally:
        server.stop()
        payload_host.release([("rdtsessrang_blob1", -1)])
        srv.shutdown()
        cli.close()


# ==== client: close() teardown (ISSUE 4 satellite) =================================
def test_close_tears_down_peers_and_restart_cycle_does_not_accumulate():
    from raydp_tpu.runtime.rpc import MethodDispatcher, RpcServer

    class _Peer:
        def store_reap(self):
            return True

    server = RpcServer(MethodDispatcher(_Peer()), port=0, name="peer")
    addr = f"{server.address[0]}:{server.address[1]}"
    srv = ObjectStoreServer("sessclose0001")
    cli = ObjectStoreClient(srv, "sessclose0001")
    try:
        clients = []
        for _ in range(3):  # executor-restart cycle: connect → close → repeat
            peer = cli._peer(addr)
            assert cli._peer(addr) is peer  # cached, not re-dialed
            assert len(cli._peers) == 1
            clients.append(peer)
            cli.close()
            assert not cli._peers and not cli._attached
            assert peer._closed
        assert all(c._closed for c in clients)
    finally:
        server.stop()
        srv.shutdown()
