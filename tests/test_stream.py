"""Continuous pipelines (ISSUE 15): sources, incremental epochs, windowed
aggregations, the exactly-once replay contract, and online training.

Three layers:

- **source units** — no runtime: epoch assignment, the bounded replay
  journal, file-tail chunking — each source's ``replay`` must be
  byte-identical to the original emission (that determinism IS the
  exactly-once contract).
- **pipeline integration** — a real 2-executor session: micro-batch epochs
  run as engine actions, results publish through the epoch ledger
  (``EpochStream`` consumes them in order), windows merge per-epoch
  partials with pandas-checked values, and close() leaves zero orphaned
  store objects.
- **online training** — ``partial_fit`` consumes a pipeline through the
  feed plane, updating params across epochs with per-epoch metrics and an
  export cadence.

The seeded chaos legs (executor crash mid-epoch, ``stream.epoch:drop``)
live in tests/test_chaos.py with the rest of the injection matrix; the
serving hot-swap race lives in tests/test_serve.py.
"""

import os
import time

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from raydp_tpu import stream
from raydp_tpu.stream import (
    FileTailSource,
    ReplayLogSource,
    StreamError,
    SyntheticSource,
)


def _table(seed, rows=32, keys=4):
    rng = np.random.RandomState(seed)
    return pa.table({
        "k": rng.randint(0, keys, rows),
        "v": rng.randint(0, 100, rows).astype(np.int64),
    })


# ---------------------------------------------------------------------------
# source units
# ---------------------------------------------------------------------------

def test_synthetic_source_epochs_monotonic_and_replay_identical():
    src = SyntheticSource(_table, max_epochs=5)
    got = []
    while True:
        mb = src.next_batch(timeout_s=0.1)
        if mb is None:
            break
        got.append(mb)
    assert [mb.epoch for mb in got] == [0, 1, 2, 3, 4]
    assert src.exhausted and src.epochs_emitted == 5
    for mb in got:
        assert src.replay(mb.epoch).equals(mb.table)


def test_source_journal_bounded_by_retention(monkeypatch):
    monkeypatch.setenv("RDT_STREAM_RETAIN", "3")
    src = SyntheticSource(_table, max_epochs=6)
    while src.next_batch(timeout_s=0.1) is not None:
        pass
    # synthetic journal entries are just epoch ids, but the retention
    # window still governs which epochs may replay
    assert len(src._journal) == 3
    assert src.replay(5).equals(_table(5))
    with pytest.raises(StreamError):
        src.replay(1)


def test_replay_log_source_is_its_own_journal():
    log = [_table(i, rows=8) for i in range(3)]
    src = ReplayLogSource(log)
    mbs = []
    while not src.exhausted:
        mb = src.next_batch(timeout_s=0.1)
        assert mb is not None
        mbs.append(mb)
    assert [m.epoch for m in mbs] == [0, 1, 2]
    assert src.replay(0).equals(log[0])  # retention never drops the log
    with pytest.raises(StreamError):
        src.replay(7)


def test_file_tail_source_chunks_and_replays(tmp_path):
    import pyarrow.parquet as pq

    big = _table(0, rows=10)
    pq.write_table(big, str(tmp_path / "a0.parquet"))
    pq.write_table(_table(1, rows=4), str(tmp_path / "a1.parquet"))
    src = FileTailSource(str(tmp_path), rows_per_batch=4)
    batches = []
    while True:
        mb = src.next_batch(timeout_s=0.2)
        if mb is None:
            break
        batches.append(mb)
    # 10-row file chunks to 4+4+2, then the next file in sorted order
    assert [b.table.num_rows for b in batches] == [4, 4, 2, 4]
    assert pa.concat_tables([b.table for b in batches[:3]]).equals(big)
    for b in batches:
        assert src.replay(b.epoch).equals(b.table)
    # a file appearing later is picked up by a subsequent poll
    pq.write_table(_table(2, rows=3), str(tmp_path / "a2.parquet"))
    mb = src.next_batch(timeout_s=2.0)
    assert mb is not None and mb.epoch == 4 and mb.table.num_rows == 3


# ---------------------------------------------------------------------------
# pipeline integration (real session)
# ---------------------------------------------------------------------------

def _expected_window(tables, keys=("k",)):
    pdf = pa.concat_tables(tables).to_pandas()
    g = pdf.groupby("k")["v"]
    out = pd.DataFrame({
        "v_sum": g.sum(),
        "v_mean": g.sum() / g.count(),  # sum/count: the partials' mean
        "v_count": g.count(),
    }).reset_index().sort_values("k").reset_index(drop=True)
    return out


def _store_settles_at(client, count, timeout=20.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if client.stats()["num_objects"] == count:
            return True
        time.sleep(0.1)
    return client.stats()["num_objects"] == count


def test_pipeline_epochs_windows_and_ledger_consumer(session):
    from raydp_tpu.etl.expressions import col
    from raydp_tpu.runtime.object_store import get_client

    client = get_client()
    before = client.stats()["num_objects"]
    src = SyntheticSource(_table, max_epochs=4)
    pipe = stream.read_stream(src).transform(
        lambda df: df.filter(col("v") >= 0)).window(
        size=2, keys=["k"], aggs={"v": ["sum", "mean", "count"]})
    consumer = pipe.epoch_stream()
    results = list(pipe.epochs())
    assert [er.epoch for er in results] == [0, 1, 2, 3]
    assert all(er.input_rows == 32 for er in results)
    # epoch results are the transformed micro-batches, fetchable by ref
    assert results[0].table().equals(_table(0))
    # tumbling windows close at epochs 1 and 3 with pandas-checked values
    closed = [(er.epoch, w) for er in results for w in er.windows]
    assert [(e, w.start, w.end) for e, w in closed] == [(1, 0, 1), (3, 2, 3)]
    for _, w in closed:
        expect = _expected_window([_table(w.start), _table(w.end)])
        got = w.table.to_pandas()
        assert list(got.columns) == ["k", "v_sum", "v_mean", "v_count"]
        pd.testing.assert_frame_equal(got, expect, check_dtype=False)
    # the decoupled ledger consumer sees every epoch, in order
    seen = []
    while True:
        item = consumer.next(timeout_s=2.0)
        if item is None:
            break
        seen.append(item)
    assert [e for e, _ in seen] == [0, 1, 2, 3]
    assert all(t.equals(_table(e)) for e, t in seen)
    rep = pipe.report()
    assert rep["epochs"] == 4 and rep["windows_closed"] == 2
    assert rep["replays"] == 0
    pipe.close()
    # the pipeline owns every blob it sealed: close frees them all
    assert _store_settles_at(client, before)


def test_sliding_window_and_consumer_replay_of_lost_result(session):
    from raydp_tpu.runtime.object_store import get_client

    client = get_client()
    before = client.stats()["num_objects"]
    pipe = stream.read_stream(SyntheticSource(_table, max_epochs=3)).window(
        size=2, slide=1, keys=["k"], aggs={"v": "sum"})
    results = list(pipe.epochs())
    # slide=1: a window closes at every epoch once the first fills
    assert [(w.start, w.end) for er in results for w in er.windows] \
        == [(0, 1), (1, 2)]
    # lose epoch 1's PUBLISHED result blob behind the ledger's back: a
    # consumer fetch must replay it (gen+1 re-seal) and still yield the
    # exact original table
    with pipe._lock:
        _, ref = pipe._results[1]
    client.free([ref])
    consumer = pipe.epoch_stream(from_epoch=1)
    epoch, table = consumer.next(timeout_s=5.0)
    assert epoch == 1 and table.equals(_table(1))
    assert pipe.report()["replays"] == 1
    with pipe._lock:
        gen, _ = pipe._results[1]
    assert gen >= 2  # the re-seal superseded the lost generation
    pipe.close()
    assert _store_settles_at(client, before)


def test_pipeline_background_thread_and_stop(session):
    pipe = stream.read_stream(
        SyntheticSource(_table, max_epochs=3))
    seen = []
    pipe.start(sink=lambda er: seen.append(er.epoch))
    deadline = time.monotonic() + 30
    while len(seen) < 3 and time.monotonic() < deadline:
        time.sleep(0.05)
    pipe.stop()
    assert seen == [0, 1, 2]
    pipe.close()


def test_transform_runs_as_engine_action_with_static_join(session):
    """The epoch transform has the whole DataFrame surface — here a join
    against a static dimension frame of the same session."""
    dim = session.createDataFrame(
        pd.DataFrame({"k": [0, 1, 2, 3], "name": ["a", "b", "c", "d"]}),
        num_partitions=1)
    pipe = stream.read_stream(SyntheticSource(_table, max_epochs=2)) \
        .transform(lambda df: df.join(dim, on="k"))
    results = list(pipe.epochs())
    for er in results:
        got = er.table().to_pandas()
        expect = _table(er.epoch).to_pandas().merge(
            pd.DataFrame({"k": [0, 1, 2, 3],
                          "name": ["a", "b", "c", "d"]}), on="k")
        assert sorted(got["name"]) == sorted(expect["name"])
        assert got["v"].sum() == expect["v"].sum()
    pipe.close()


# ---------------------------------------------------------------------------
# online training
# ---------------------------------------------------------------------------

def _reg_table(epoch, rows=64):
    rng = np.random.RandomState(epoch)
    x = rng.random_sample((rows, 2))
    y = x @ np.array([2.0, -3.0]) + 1.0
    return pa.table({"x1": x[:, 0], "x2": x[:, 1], "y": y})


def test_partial_fit_flax_updates_params_with_per_epoch_metrics(
        session, tmp_path):
    import optax

    from raydp_tpu.models import MLP
    from raydp_tpu.runtime.object_store import get_client
    from raydp_tpu.train import FlaxEstimator

    client = get_client()
    before = client.stats()["num_objects"]
    est = FlaxEstimator(model=MLP(features=(8,), use_batch_norm=False),
                        optimizer=optax.adam(1e-2), loss="mse",
                        feature_columns=["x1", "x2"], label_column="y",
                        batch_size=32, num_epochs=1)
    pipe = stream.read_stream(SyntheticSource(_reg_table, max_epochs=3))
    res = est.partial_fit(pipe, export_every=2, export_dir=str(tmp_path))
    assert res.epochs == 3
    assert [h["epoch"] for h in res.history] == [0, 1, 2]
    for h in res.history:
        assert h["steps"] == 2                 # 64 rows / batch 32
        assert np.isfinite(h["train_loss"])
    # params persisted ACROSS epochs (online, not refit-per-epoch): the
    # model after 3 epochs differs from after 1, and get_model works
    assert res.exports == [(1, os.path.join(str(tmp_path), "v1"))]
    assert os.path.isdir(res.exports[0][1])
    assert est.get_model()["params"] is not None
    pipe.close()
    assert _store_settles_at(client, before)


def test_partial_fit_consumes_epoch_stream_of_background_pipeline(session):
    """The decoupled shape: the pipeline runs on its background thread
    publishing to the ledger while partial_fit follows an EpochStream."""
    import optax

    from raydp_tpu.models import MLP
    from raydp_tpu.train import FlaxEstimator

    est = FlaxEstimator(model=MLP(features=(8,), use_batch_norm=False),
                        optimizer=optax.adam(1e-2), loss="mse",
                        feature_columns=["x1", "x2"], label_column="y",
                        batch_size=32, num_epochs=1)
    pipe = stream.read_stream(SyntheticSource(_reg_table, max_epochs=2))
    consumer = pipe.epoch_stream()
    pipe.start()
    try:
        res = est.partial_fit(consumer, timeout_s=5.0)
        assert res.epochs == 2
        assert [h["epoch"] for h in res.history] == [0, 1]
    finally:
        pipe.close()


def test_partial_fit_keras_incremental(session, tmp_path):
    from raydp_tpu.train import KerasEstimator

    keras = pytest.importorskip("keras")
    model = keras.Sequential([
        keras.layers.Input(shape=(2,)),
        keras.layers.Dense(4, activation="relu"),
        keras.layers.Dense(1),
    ])
    est = KerasEstimator(model=model, optimizer="adam", loss="mse",
                         feature_columns=["x1", "x2"], label_column="y",
                         batch_size=32, num_epochs=1)
    pipe = stream.read_stream(SyntheticSource(_reg_table, max_epochs=2))
    res = est.partial_fit(pipe)
    assert res.epochs == 2
    assert all(np.isfinite(h["train_loss"]) for h in res.history)
    assert est.get_model() is not None
    pipe.close()
