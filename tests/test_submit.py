"""rdt-submit CLI (parity: bin/raydp-submit — conf handoff into the session,
exit-code propagation)."""

import os
import subprocess
import sys
import textwrap


def _run(args, cwd):
    env = dict(os.environ)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.run(
        [sys.executable, "-m", "raydp_tpu.cli.submit"] + args,
        cwd=cwd, env=env, capture_output=True, text=True, timeout=300)


def test_submit_conf_handoff(tmp_path):
    script = tmp_path / "job.py"
    script.write_text(textwrap.dedent("""
        import raydp_tpu
        session = raydp_tpu.init("submitted")   # all defaults in code
        print("EXECUTORS=%d" % len(session.executors))
        print("CONF=%s" % session.config.get("raydp.tpu.custom.key"))
        raydp_tpu.stop()
    """))
    proc = _run(["--num-executors", "2",
                 "--conf", "raydp.tpu.custom.key=hello",
                 str(script)], cwd=str(tmp_path))
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "EXECUTORS=2" in proc.stdout
    assert "CONF=hello" in proc.stdout


def test_submit_explicit_args_win(tmp_path):
    script = tmp_path / "job.py"
    script.write_text(textwrap.dedent("""
        import raydp_tpu
        session = raydp_tpu.init("submitted", num_executors=1)
        print("EXECUTORS=%d" % len(session.executors))
        raydp_tpu.stop()
    """))
    proc = _run(["--num-executors", "3", str(script)], cwd=str(tmp_path))
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "EXECUTORS=1" in proc.stdout


def test_submit_exit_code_and_args_passthrough(tmp_path):
    script = tmp_path / "job.py"
    script.write_text(textwrap.dedent("""
        import sys
        assert sys.argv[1:] == ["--flag", "value"]
        sys.exit(7)
    """))
    proc = _run([str(script), "--flag", "value"], cwd=str(tmp_path))
    assert proc.returncode == 7


def test_submit_missing_script(tmp_path):
    proc = _run(["/nonexistent/script.py"], cwd=str(tmp_path))
    assert proc.returncode != 0
    assert "not found" in proc.stderr


def test_submit_py_files(tmp_path):
    """--py-files makes sidecar modules importable in the submitted driver
    (parity: the reference's raydp-submit --py-files examples,
    examples/test_raydp_submit_pyfiles.py + test_pyfile.py)."""
    lib_dir = tmp_path / "deps"
    lib_dir.mkdir()
    (lib_dir / "helper_mod.py").write_text("VALUE = 41\n")
    # the bare .py lives in a third directory (NOT the script's dir, which
    # python puts on sys.path anyway) with a sibling that must NOT become
    # importable: only the named file ships, as with spark-submit
    other_dir = tmp_path / "elsewhere"
    other_dir.mkdir()
    (other_dir / "single.py").write_text("OTHER = 1\n")
    (other_dir / "sibling_mod.py").write_text("LEAKED = True\n")

    script_dir = tmp_path / "app"
    script_dir.mkdir()
    script = script_dir / "job.py"
    script.write_text(textwrap.dedent("""
        import helper_mod
        import single
        try:
            import sibling_mod
            print("SIBLING_LEAKED")
        except ImportError:
            pass
        print("SUM=%d" % (helper_mod.VALUE + single.OTHER))
    """))
    proc = _run(["--py-files", f"{lib_dir},{other_dir / 'single.py'}",
                 str(script)], cwd=str(tmp_path))
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "SUM=42" in proc.stdout
    assert "SIBLING_LEAKED" not in proc.stdout


def test_submit_py_files_missing(tmp_path):
    script = tmp_path / "job.py"
    script.write_text("print('hi')\n")
    proc = _run(["--py-files", "/nonexistent/dep.py", str(script)],
                cwd=str(tmp_path))
    assert proc.returncode != 0
    assert "not found" in proc.stderr
