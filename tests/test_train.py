"""Estimator tests (parity: reference test_torch.py — synthetic linear data,
object-store vs parquet conversion paths, shape-only model assertions)."""

import numpy as np
import pandas as pd
import pytest

from raydp_tpu.etl.expressions import col
from raydp_tpu.models import MLP
from raydp_tpu.train import FlaxEstimator


def _linear_df(session, n=2048):
    rng = np.random.RandomState(0)
    x = rng.random_sample((n, 2)).astype(np.float64)
    y = x @ np.array([2.0, -3.0]) + 1.0 + rng.normal(0, 0.01, n)
    pdf = pd.DataFrame({"x1": x[:, 0], "x2": x[:, 1], "y": y})
    return session.createDataFrame(pdf, num_partitions=4)


@pytest.mark.parametrize("use_fs_directory", [False, True])
def test_estimator_fit_on_frame(session, tmp_path, use_fs_directory):
    import optax

    df = _linear_df(session)
    train_df, test_df = df.randomSplit([0.75, 0.25], seed=1)
    est = FlaxEstimator(
        model=MLP(features=(16,), use_batch_norm=False),
        optimizer=optax.adam(1e-2),
        loss="mse",
        feature_columns=["x1", "x2"],
        label_column="y",
        batch_size=64,
        num_epochs=3,
        metrics=["mae", "mse"],
    )
    kwargs = {"fs_directory": str(tmp_path / "spill")} if use_fs_directory else {}
    result = est.fit_on_frame(train_df, test_df, **kwargs)
    assert len(result.history) == 3
    last = result.history[-1]
    assert last["train_loss"] < result.history[0]["train_loss"]
    assert "eval_mae" in last and "train_mse" in last

    model = est.get_model()
    kernel = model["params"]["Dense_0"]["kernel"]
    assert kernel.shape == (2, 16)


def test_estimator_predict(session):
    """predict() runs the trained model over a dataset's feature columns,
    covers the full row count (ragged final batch included), and matches a
    manual model.apply on the same rows."""
    import jax
    import optax

    from raydp_tpu.data.dataset import from_frame

    df = _linear_df(session, n=1000)   # 1000 % 64 != 0: exercises the tail
    est = FlaxEstimator(
        model=MLP(features=(16,), use_batch_norm=False),
        optimizer=optax.adam(1e-2),
        loss="mse",
        feature_columns=["x1", "x2"],
        label_column="y",
        batch_size=64,
        num_epochs=2,
    )
    ds = from_frame(df)
    est.fit(ds)

    preds = est.predict(ds)
    assert preds.shape == (1000,)
    assert np.isfinite(preds).all()

    table = ds.to_arrow()
    x = np.stack([table.column("x1").to_numpy(),
                  table.column("x2").to_numpy()], axis=1).astype(np.float32)
    manual = MLP(features=(16,), use_batch_norm=False).apply(
        {"params": jax.tree.map(np.asarray, est.get_model()["params"])}, x)
    np.testing.assert_allclose(preds, np.asarray(manual).squeeze(-1),
                               rtol=1e-5, atol=1e-6)
    # rough sanity: a fitted linear model correlates with the labels
    y = table.column("y").to_numpy()
    assert np.corrcoef(preds, y)[0, 1] > 0.5


def test_estimator_batchnorm_model(session):
    import optax

    from raydp_tpu.models import NYCTaxiModel

    df = _linear_df(session, n=1024)
    est = FlaxEstimator(
        model=NYCTaxiModel(),
        optimizer=optax.adam(1e-3),
        loss="smooth_l1",
        feature_columns=["x1", "x2"],
        label_column="y",
        batch_size=128,
        num_epochs=2,
    )
    result = est.fit_on_frame(df)
    assert len(result.history) == 2
    model = est.get_model()
    assert "batch_stats" in model


def test_estimator_creators_and_retry(session):
    """Creator callables (parity torch/estimator.py:177-220) + checkpoint resume."""
    import optax

    df = _linear_df(session, n=512)
    est = FlaxEstimator(
        model_creator=lambda: MLP(features=(8,), use_batch_norm=False),
        optimizer_creator=lambda: optax.sgd(1e-2),
        loss="mse",
        feature_columns=["x1", "x2"],
        label_column="y",
        batch_size=64,
        num_epochs=2,
    )
    result = est.fit_on_frame(df, max_retries=1)
    assert len(result.history) == 2
    assert result.checkpoint_dir is not None
    import os
    assert any(d.startswith("step_") for d in os.listdir(result.checkpoint_dir))


def test_estimator_sharded_batch(session):
    """Batch lands sharded over the 8-device data axis; loss still converges."""
    import jax
    import optax

    from raydp_tpu.parallel import MeshSpec, make_mesh

    assert len(jax.devices()) == 8
    mesh = make_mesh(MeshSpec(data=8))
    df = _linear_df(session, n=2048)
    est = FlaxEstimator(
        model=MLP(features=(16,), use_batch_norm=False),
        optimizer=optax.adam(1e-2),
        loss="mse",
        feature_columns=["x1", "x2"],
        label_column="y",
        batch_size=256,
        num_epochs=2,
        mesh=mesh,
    )
    result = est.fit_on_frame(df)
    assert result.history[-1]["train_loss"] < result.history[0]["train_loss"]


def test_steps_per_dispatch_chain_parity(session, monkeypatch):
    """Chaining k train steps into one lax.scan dispatch must be numerically
    IDENTICAL to dispatching each batch: same update sequence, same loss
    history (the chain only amortizes host->device round trips). Also covers
    the epoch-remainder stack (steps % k != 0) and BatchNorm state threading
    through the scan carry."""
    import optax

    from raydp_tpu.data import from_frame

    df = _linear_df(session, n=1344)  # 21 batches of 64 → 21 % 4 != 0
    ds = from_frame(df)
    # pin the STREAMING feed: the device-resident path neither chains nor
    # streams, which would make this parity check vacuous
    monkeypatch.setenv("RDT_DEVICE_CACHE", "0")

    def run(chain):
        est = FlaxEstimator(
            model=MLP(features=(8,), use_batch_norm=True),
            optimizer=optax.adam(1e-2),
            loss="mse",
            feature_columns=["x1", "x2"],
            label_column="y",
            batch_size=64,
            num_epochs=2,
            shuffle=False,
            seed=0,
            steps_per_dispatch=chain,
        )
        return est.fit(ds)

    plain = run(1)
    chained = run(4)
    assert [r["steps"] for r in chained.history] == \
        [r["steps"] for r in plain.history]
    for a, b in zip(plain.history, chained.history):
        np.testing.assert_allclose(a["train_loss"], b["train_loss"],
                                   rtol=1e-5, atol=1e-6)


def test_steps_per_dispatch_ragged_tail(session):
    """drop_last=False + chaining: the smaller epoch-tail batch cannot stack
    with full batches — the feed must flush and send it alone, and training
    must see every row (code-review r4 finding). A ragged batch only shards
    on a size-1 data axis (same rule the eval feed applies), so this runs on
    a single-device mesh."""
    import jax
    import optax

    from raydp_tpu.data import from_frame
    from raydp_tpu.parallel import MeshSpec, make_mesh

    df = _linear_df(session, n=1350)  # 21 full batches of 64 + a 6-row tail
    ds = from_frame(df)
    est = FlaxEstimator(
        model=MLP(features=(8,), use_batch_norm=False),
        optimizer=optax.adam(1e-2),
        loss="mse",
        feature_columns=["x1", "x2"],
        label_column="y",
        batch_size=64,
        num_epochs=2,
        shuffle=False,
        drop_last=False,
        steps_per_dispatch=4,
        mesh=make_mesh(MeshSpec(data=1), devices=jax.devices()[:1]),
    )
    result = est.fit(ds)
    assert [r["steps"] for r in result.history] == [22, 22]
    assert np.isfinite(result.history[-1]["train_loss"])


def test_device_cache_parity_and_fallback(session, monkeypatch):
    """The device-resident epoch path (whole epoch = one jitted scan over
    HBM-pinned arrays) must produce exactly the streaming feed's update
    sequence at shuffle=False — same batches, same order — and the
    ``RDT_DEVICE_CACHE`` / budget knobs must force the streaming fallback."""
    import optax

    from raydp_tpu.data import from_frame

    df = _linear_df(session, n=1344)
    ds = from_frame(df)
    # pin the knobs: ambient RDT_DEVICE_CACHE*=... (e.g. exported while
    # debugging the streaming path) must not flip the first run
    monkeypatch.setenv("RDT_DEVICE_CACHE", "1")
    monkeypatch.delenv("RDT_DEVICE_CACHE_MB", raising=False)

    eval_ds = from_frame(_linear_df(session, n=333))  # ragged vs batch 64

    def run():
        est = FlaxEstimator(
            model=MLP(features=(8,), use_batch_norm=True),
            optimizer=optax.adam(1e-2),
            loss="mse",
            feature_columns=["x1", "x2"],
            label_column="y",
            batch_size=64,
            num_epochs=2,
            shuffle=False,
            seed=0,
            metrics=["mae"],
        )
        return est.fit(ds, eval_ds)

    resident = run()
    # the resident path does no host-side feeding at all
    assert all(r["feed_time_s"] == 0.0 for r in resident.history)

    monkeypatch.setenv("RDT_DEVICE_CACHE", "0")
    streamed = run()
    assert any(r["feed_time_s"] > 0.0 for r in streamed.history)

    assert [r["steps"] for r in resident.history] == \
        [r["steps"] for r in streamed.history]
    for a, b in zip(resident.history, streamed.history):
        np.testing.assert_allclose(a["train_loss"], b["train_loss"],
                                   rtol=1e-5, atol=1e-6)
        # the resident EVAL scan (+ tail rule) must match the streaming
        # eval pass exactly too
        np.testing.assert_allclose(a["eval_loss"], b["eval_loss"],
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(a["eval_mae"], b["eval_mae"],
                                   rtol=1e-5, atol=1e-6)

    # a zero budget must also fall back (estimate > cap)
    monkeypatch.setenv("RDT_DEVICE_CACHE", "1")
    monkeypatch.setenv("RDT_DEVICE_CACHE_MB", "0")
    capped = run()
    assert any(r["feed_time_s"] > 0.0 for r in capped.history)


def test_device_cache_shuffled_training_converges(session, monkeypatch):
    """With shuffle=True the resident path shuffles via an on-device
    permutation per epoch: training must still converge on the linear task
    and walk a different batch order every epoch (loss histories differ from
    an unshuffled run)."""
    import optax

    from raydp_tpu.data import from_frame

    df = _linear_df(session, n=1344)
    ds = from_frame(df)
    monkeypatch.setenv("RDT_DEVICE_CACHE", "1")
    monkeypatch.delenv("RDT_DEVICE_CACHE_MB", raising=False)

    def run(shuffle):
        est = FlaxEstimator(
            model=MLP(features=(16,), use_batch_norm=False),
            optimizer=optax.adam(1e-2),
            loss="mse",
            feature_columns=["x1", "x2"],
            label_column="y",
            batch_size=64,
            num_epochs=4,
            shuffle=shuffle,
            seed=0,
        )
        return est.fit(ds)

    result = run(True)
    assert all(r["feed_time_s"] == 0.0 for r in result.history)
    assert result.history[-1]["train_loss"] < result.history[0]["train_loss"]

    # the permutation must actually reorder rows: an unshuffled twin walks a
    # different batch sequence, so its loss history cannot coincide
    unshuffled = run(False)
    assert any(
        abs(a["train_loss"] - b["train_loss"]) > 1e-9
        for a, b in zip(result.history, unshuffled.history))


def test_checkpoint_interval(session, tmp_path):
    """checkpoint_interval=N saves every N-th epoch plus always the final one
    (per-epoch checkpointing is reference parity and stays the default; the
    knob exists because a resident epoch can be cheaper than its save)."""
    import os

    import optax

    df = _linear_df(session, n=512)
    est = FlaxEstimator(
        model=MLP(features=(8,), use_batch_norm=False),
        optimizer=optax.adam(1e-2),
        loss="mse",
        feature_columns=["x1", "x2"],
        label_column="y",
        batch_size=64,
        num_epochs=5,
        checkpoint_dir=str(tmp_path / "ck"),
        checkpoint_interval=3,
    )
    est.fit_on_frame(df)
    steps = sorted(d for d in os.listdir(tmp_path / "ck")
                   if d.startswith("step_"))
    # epochs 0..4: saves at epoch 2 (3rd) and epoch 4 (final)
    assert steps == ["step_2", "step_4"]


def test_retry_before_first_interval_save_rebuilds(session):
    """A failure before the first interval checkpoint has nothing to
    restore; the retry must rebuild the state from scratch (the failed
    state's buffers may be donated away), not continue on dead buffers."""
    import optax

    calls = {"n": 0}

    def boom(report):
        if calls["n"] == 0:
            calls["n"] += 1
            raise RuntimeError("transient failure injected at epoch 0")

    df = _linear_df(session, n=512)
    est = FlaxEstimator(
        model=MLP(features=(8,), use_batch_norm=False),
        optimizer=optax.adam(1e-2),
        loss="mse",
        feature_columns=["x1", "x2"],
        label_column="y",
        batch_size=64,
        num_epochs=2,
        checkpoint_interval=10,  # no save before the injected failure
        callbacks=[boom],
    )
    result = est.fit_on_frame(df, max_retries=1)
    assert len(result.history) == 2
    assert np.isfinite(result.history[-1]["train_loss"])


def test_retry_ignores_stale_checkpoint_dir(session, tmp_path):
    """A fresh fit reusing a checkpoint_dir from an EARLIER run must not
    adopt that run's checkpoint on retry — only checkpoints this run wrote
    (or an explicit resume) may restore; otherwise the retry silently
    returns the old model and history."""
    import optax

    df = _linear_df(session, n=512)
    ck = str(tmp_path / "ck")

    def make(**kw):
        return FlaxEstimator(
            model=MLP(features=(8,), use_batch_norm=False),
            optimizer=optax.adam(1e-2),
            loss="mse",
            feature_columns=["x1", "x2"],
            label_column="y",
            batch_size=64,
            checkpoint_dir=ck,
            **kw,
        )

    make(num_epochs=4).fit_on_frame(df)  # run A leaves step_3 behind

    calls = {"n": 0}

    def boom(report):
        if calls["n"] == 0:
            calls["n"] += 1
            raise RuntimeError("transient")

    result = make(num_epochs=2, checkpoint_interval=10,
                  callbacks=[boom]).fit_on_frame(df, max_retries=1)
    # adopted-stale would return run A's 4-epoch history; fresh rebuild
    # trains exactly this run's 2 epochs
    assert len(result.history) == 2

    # the harder mixed case: run C saves step_0, then fails — the retry
    # must restore run C's OWN step_0 (and retention must not have pruned
    # it in favor of run A's higher-numbered stale steps, which latest-step
    # selection would otherwise adopt)
    calls2 = {"n": 0}

    def boom_epoch1(report):
        if report["epoch"] == 1 and calls2["n"] == 0:
            calls2["n"] += 1
            raise RuntimeError("transient at epoch 1")

    result_c = make(num_epochs=2, checkpoint_interval=1,
                    callbacks=[boom_epoch1]).fit_on_frame(df, max_retries=1)
    # run C resumed from its own epoch-0 save: exactly 2 epoch reports,
    # not run A's 4
    assert len(result_c.history) == 2
