"""Transformer LM + flash attention tests.

Covers the long-context tier: flash kernel vs dense reference (fwd + grad,
both the jnp path and the Pallas kernel in interpret mode), ring-vs-dense
equivalence through the full model on a sequence-sharded mesh, and a short
training-loss check.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raydp_tpu.ops.flash_attention import flash_attention
from raydp_tpu.ops.ring_attention import dense_attention


def _qkv(b=2, t=128, h=2, d=32, seed=0):
    rng = np.random.RandomState(seed)
    return tuple(jnp.asarray(rng.randn(b, t, h, d).astype(np.float32)) * 0.3
                 for _ in range(3))


@pytest.mark.parametrize("causal", [True, False])
def test_flash_matches_dense(causal):
    q, k, v = _qkv()
    ref = dense_attention(q, k, v, causal=causal)
    got = flash_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-5)


def test_flash_pallas_interpret_matches_dense():
    q, k, v = _qkv(t=256, d=128)
    ref = dense_attention(q, k, v, causal=True)
    got = flash_attention(q, k, v, causal=True, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-5)


def test_flash_grads_match_dense():
    q, k, v = _qkv(t=64)

    def loss(f):
        return lambda q, k, v: jnp.sum(f(q, k, v, causal=True) ** 2)

    g_ref = jax.grad(loss(dense_attention), argnums=(0, 1, 2))(q, k, v)
    g_got = jax.grad(loss(flash_attention), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_got):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), atol=1e-4)


@pytest.mark.parametrize("blocks", [(256, 256), (64, 64), (64, 128)])
@pytest.mark.parametrize("causal", [True, False])
def test_flash_pallas_bwd_interpret_matches_dense(causal, blocks):
    """The Pallas dq / dkdv kernels (interpret mode) against dense grads —
    the hardware backward path, exercised on CPU. The sub-256 block cases run
    multi-block grids (up to 4x4), covering cross-block accumulation, scratch
    init/finalize, the causal block skip, and rectangular blk_q != blk_k."""
    bq, bk = blocks
    q, k, v = _qkv(t=256, d=64)

    def loss(f, **kw):
        return lambda q, k, v: jnp.sum(f(q, k, v, causal=causal, **kw) ** 2)

    g_ref = jax.grad(loss(dense_attention), argnums=(0, 1, 2))(q, k, v)
    g_got = jax.grad(loss(flash_attention, interpret=True,
                          block_q=bq, block_k=bk),
                     argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_got):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), atol=1e-4)


def _tokens(b, t, vocab, seed=0):
    rng = np.random.RandomState(seed)
    return jnp.asarray(rng.randint(0, vocab, size=(b, t)).astype(np.int32))


def test_lm_forward_shapes():
    from raydp_tpu.models import TransformerLM

    model = TransformerLM(vocab_size=64, dim=32, num_heads=2, num_layers=2,
                          attention="dense")
    tokens = _tokens(2, 16, 64)
    variables = model.init(jax.random.PRNGKey(0), tokens)
    logits = model.apply(variables, tokens)
    assert logits.shape == (2, 16, 64)
    assert logits.dtype == jnp.float32


def test_lm_ring_matches_dense_on_mesh():
    """Full model, sequence sharded over seq=4: ring attention output equals
    the dense single-device reference."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from raydp_tpu.models import TransformerLM
    from raydp_tpu.parallel import MeshSpec, make_mesh

    mesh = make_mesh(MeshSpec(data=2, seq=4))
    vocab, b, t = 64, 4, 32

    dense_model = TransformerLM(vocab_size=vocab, dim=32, num_heads=2,
                                num_layers=2, attention="dense")
    ring_model = TransformerLM(vocab_size=vocab, dim=32, num_heads=2,
                               num_layers=2, attention="ring", mesh=mesh)
    tokens = _tokens(b, t, vocab)
    variables = dense_model.init(jax.random.PRNGKey(0), tokens)

    ref = dense_model.apply(variables, tokens)

    sharded_tokens = jax.device_put(
        tokens, NamedSharding(mesh, P("data", "seq")))
    with mesh:
        got = jax.jit(ring_model.apply)(variables, sharded_tokens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-4, rtol=2e-4)


def test_lm_tensor_parallel_matches_replicated():
    """Megatron-split params over tensor=2: one train step produces the same
    loss and updated params as the fully-replicated run — GSPMD inserts the
    per-block all-reduces, the math is unchanged."""
    import optax

    from raydp_tpu.models import TransformerLM, lm_loss, \
        transformer_param_rules
    from raydp_tpu.parallel import (
        MeshSpec, batch_sharding, make_mesh, param_sharding_rules,
    )

    vocab, b, t = 64, 8, 32
    model = TransformerLM(vocab_size=vocab, dim=32, num_heads=2, num_layers=2,
                          attention="dense")
    tokens = _tokens(b, t, vocab)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    tx = optax.sgd(1e-1)

    def one_step(mesh, rules):
        shardings_of = param_sharding_rules(mesh, rules)
        p = jax.tree.map(jax.device_put, params, shardings_of(params))
        opt = jax.tree.map(jax.device_put, tx.init(params),
                           shardings_of(tx.init(params)))
        toks = jax.device_put(tokens, batch_sharding(mesh))

        @jax.jit
        def step(p, opt, toks):
            loss, grads = jax.value_and_grad(
                lambda p_: lm_loss(model.apply({"params": p_}, toks), toks))(p)
            upd, opt = tx.update(grads, opt)
            return optax.apply_updates(p, upd), loss

        with mesh:
            new_p, loss = step(p, opt, toks)
        return new_p, float(loss)

    p_rep, l_rep = one_step(make_mesh(MeshSpec()), None)
    tp_mesh = make_mesh(MeshSpec(data=4, tensor=2))
    rules = transformer_param_rules("tensor")
    p_tp, l_tp = one_step(tp_mesh, rules)

    np.testing.assert_allclose(l_tp, l_rep, rtol=1e-5)

    flat_tp = {jax.tree_util.keystr(k): v
               for k, v in jax.tree_util.tree_flatten_with_path(p_tp)[0]}
    for k, v in jax.tree_util.tree_flatten_with_path(p_rep)[0]:
        key = jax.tree_util.keystr(k)
        np.testing.assert_allclose(np.asarray(flat_tp[key]), np.asarray(v),
                                   atol=2e-5, err_msg=key)

    # the split actually took: a q kernel holds half its heads per shard
    qkey = next(k for k in flat_tp if "attn']['q']['kernel" in k
                or "attn/q/kernel" in k)
    qarr = flat_tp[qkey]
    assert qarr.sharding.shard_shape(qarr.shape)[1] == qarr.shape[1] // 2


def test_lm_training_reduces_loss():
    import optax

    from raydp_tpu.models import TransformerLM, lm_loss

    vocab = 32
    model = TransformerLM(vocab_size=vocab, dim=64, num_heads=2, num_layers=2,
                          attention="dense")
    # learnable structure: next token = (token + 1) % vocab
    rng = np.random.RandomState(0)
    start = rng.randint(0, vocab, size=(64, 1))
    tokens = jnp.asarray((start + np.arange(24)[None, :]) % vocab,
                         dtype=jnp.int32)

    variables = model.init(jax.random.PRNGKey(0), tokens[:, :1])
    tx = optax.adam(1e-2)
    opt_state = tx.init(variables["params"])

    @jax.jit
    def step(params, opt_state, batch):
        def loss_fn(p):
            return lm_loss(model.apply({"params": p}, batch), batch)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    params = variables["params"]
    losses = []
    for i in range(30):
        params, opt_state, loss = step(params, opt_state, tokens)
        losses.append(float(loss))
    assert losses[-1] < 0.3 * losses[0], losses[::10]


def test_lm_loss_fused_matches_materialized():
    """The chunked fused lm_head+CE must equal the materialized-logits loss
    in value AND gradients (incl. the lm_head kernel, which only receives
    gradient through the fused path's explicit matmul)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from raydp_tpu.models import TransformerLM, lm_loss
    from raydp_tpu.models.transformer import lm_loss_fused

    vocab, T, B = 97, 37, 3  # odd sizes: exercises the chunk padding path
    model = TransformerLM(vocab_size=vocab, dim=32, num_heads=2,
                          num_layers=2, attention="dense")
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, vocab, size=(B, T)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    assert "lm_head" in params  # registered on the plain init path

    def loss_mat(p):
        return lm_loss(model.apply({"params": p}, tokens), tokens)

    def loss_fused(p):
        hidden = model.apply({"params": p}, tokens, return_hidden=True)
        return lm_loss_fused(hidden, p["lm_head"]["kernel"], tokens, chunk=16)

    v1, g1 = jax.value_and_grad(loss_mat)(params)
    v2, g2 = jax.value_and_grad(loss_fused)(params)
    np.testing.assert_allclose(float(v1), float(v2), rtol=1e-5)
    flat1 = jax.tree_util.tree_leaves_with_path(g1)
    g2_by_path = dict(jax.tree_util.tree_leaves_with_path(g2))
    for path, leaf in flat1:
        np.testing.assert_allclose(np.asarray(leaf),
                                   np.asarray(g2_by_path[path]),
                                   rtol=2e-4, atol=1e-6,
                                   err_msg=str(path))


def test_return_hidden_registers_head_params():
    """Init THROUGH the hidden path still creates the lm_head kernel, so a
    fused-loss training setup has the full param tree from the start."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from raydp_tpu.models import TransformerLM

    model = TransformerLM(vocab_size=64, dim=16, num_heads=2, num_layers=1,
                          attention="dense")
    tokens = jnp.asarray(np.zeros((1, 8)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens,
                        return_hidden=True)["params"]
    assert params["lm_head"]["kernel"].shape == (16, 64)
