"""Unit tests for utils (parity: reference test_spark_utils.py)."""

import pytest

from raydp_tpu.utils import divide_blocks, memory_string, parse_memory_size


def test_parse_memory_size():
    assert parse_memory_size(1024) == 1024
    assert parse_memory_size("1024") == 1024
    assert parse_memory_size("1024B") == 1024
    assert parse_memory_size("1k") == 1024
    assert parse_memory_size("1KB") == 1024
    assert parse_memory_size("1.5 GB") == int(1.5 * 2**30)
    assert parse_memory_size("2g") == 2 * 2**30
    assert parse_memory_size("1T") == 2**40
    with pytest.raises(ValueError):
        parse_memory_size("12XB")


def test_memory_string_roundtrip():
    for s in ["512MB", "1GB", "300"]:
        assert parse_memory_size(memory_string(parse_memory_size(s))) == \
            parse_memory_size(s)


def _check_equal_share(blocks, world_size, shuffle=False, seed=None):
    import math
    result = divide_blocks(blocks, world_size, shuffle=shuffle, shuffle_seed=seed)
    assert set(result.keys()) == set(range(world_size))
    expected = math.ceil(sum(blocks) / world_size)
    for rank, selected in result.items():
        total = sum(n for _, n in selected)
        assert total == expected, f"rank {rank} got {total} != {expected}"
        for idx, n in selected:
            assert 0 <= idx < len(blocks)
            assert 0 < n <= blocks[idx]


def test_divide_blocks_even():
    _check_equal_share([10, 10, 10, 10], 2)
    _check_equal_share([10, 10, 10, 10], 4)


def test_divide_blocks_uneven():
    _check_equal_share([7, 3, 11, 2, 5], 2)
    _check_equal_share([7, 3, 11, 2, 5], 3)
    _check_equal_share([1, 1, 1, 100], 3)


def test_divide_blocks_wraparound():
    # more ranks than evenly divisible blocks → wraparound duplication
    _check_equal_share([5, 6, 7], 2)


def test_divide_blocks_shuffle_deterministic():
    a = divide_blocks([4, 5, 6, 7, 8, 9], 3, shuffle=True, shuffle_seed=42)
    b = divide_blocks([4, 5, 6, 7, 8, 9], 3, shuffle=True, shuffle_seed=42)
    assert a == b
    c = divide_blocks([4, 5, 6, 7, 8, 9], 3, shuffle=True, shuffle_seed=7)
    assert a != c or True  # different seed may coincide; just must not raise


def test_divide_blocks_not_enough():
    with pytest.raises(ValueError):
        divide_blocks([5], 2)
