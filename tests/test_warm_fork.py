"""Warm-start fork plane: prototype lifecycle, pre-readiness death reaping,
chaos at the ``pool.fork`` site, and loud degrade-to-cold fallback."""

import os
import socket

import pytest

from raydp_tpu import faults, metrics
from raydp_tpu.runtime import warm_fork
from raydp_tpu.runtime.head import ENV_ACTOR_ID, ENV_HEAD, ENV_SESSION


@pytest.fixture
def fast_prototype(monkeypatch):
    """No heavy pre-imports: the prototype handshake is near-instant."""
    monkeypatch.setenv("RDT_WARM_IMPORTS", "")
    monkeypatch.setenv("RDT_WARM_FORK_WAIT_S", "10")
    metrics.reset()
    yield
    metrics.reset()


def _bootstrap_env(head_url="127.0.0.1:1"):
    """An env whose actor bootstrap dies fast (unreachable head)."""
    return {ENV_HEAD: head_url, ENV_ACTOR_ID: "a-test",
            ENV_SESSION: "s-test", "PYTHONPATH": os.getcwd()}


def test_pre_readiness_death_is_reaped(fast_prototype, tmp_path):
    """A forked worker that dies before its readiness handshake must be
    reported dead through poll (no phantom ALIVE), reaped by the prototype
    (no zombie), and must NOT latch the plane — worker death is a worker
    problem, not a warm-plane problem."""
    mgr = warm_fork.WarmForkManager(str(tmp_path))
    try:
        child = mgr.fork({}, str(tmp_path / "w0.log"), key="w0")
        rc = child.wait(timeout=15.0)
        assert rc == 1, f"bootstrap-with-no-env should exit 1, got {rc}"
        assert not os.path.exists(f"/proc/{child.pid}"), \
            "prototype left the dead fork as a zombie"
        assert mgr.available, "one worker death latched the whole plane"
        c2 = mgr.fork({}, str(tmp_path / "w1.log"), key="w1")
        assert c2.wait(timeout=15.0) == 1
        kinds = [e for e in metrics.events() if e["kind"] == "warm_fork"]
        assert len(kinds) == 2 and not any(e.get("degraded") for e in kinds)
    finally:
        mgr.stop()


def test_forked_child_kill_contract(fast_prototype, tmp_path):
    """ForkedChild honors the Popen surfaces the supervisor relies on:
    poll() is None while alive, kill() lands (the child setsid()s so the
    group kill works), and the signal death is reported as -SIGKILL."""
    # a head that accepts but never answers keeps the bootstrap alive
    trap = socket.socket()
    trap.bind(("127.0.0.1", 0))
    trap.listen(1)
    mgr = warm_fork.WarmForkManager(str(tmp_path))
    try:
        env = _bootstrap_env("127.0.0.1:%d" % trap.getsockname()[1])
        child = mgr.fork(env, str(tmp_path / "w0.log"), key="w0")
        assert child.poll() is None, "live fork reported dead"
        child.kill()
        assert child.wait(timeout=15.0) == -9
    finally:
        mgr.stop()
        trap.close()


def test_pool_fork_crash_fault_kills_fresh_fork(fast_prototype, tmp_path):
    """Chaos at ``pool.fork`` with the ``crash`` action kills the fork
    after it exists but before readiness — the flight recorder marks the
    injected death and the plane stays available for the retry."""
    faults.clear()
    faults.inject("pool.fork", "crash", times=1)
    mgr = warm_fork.WarmForkManager(str(tmp_path))
    try:
        child = mgr.fork(_bootstrap_env(), str(tmp_path / "w0.log"),
                         key="victim")
        assert child.wait(timeout=15.0) not in (None, 0)
        assert mgr.available
        evs = [e for e in metrics.events() if e["kind"] == "warm_fork"]
        assert any(e.get("injected_death") for e in evs)
        # the rule was times=1: the next fork is clean
        c2 = mgr.fork({}, str(tmp_path / "w1.log"), key="w1")
        assert c2.wait(timeout=15.0) == 1
        assert not [e for e in metrics.events()
                    if e["kind"] == "warm_fork" and e.get("key") == "w1"
                    and e.get("injected_death")]
    finally:
        faults.clear()
        mgr.stop()


def test_broken_prototype_degrades_loudly(fast_prototype, monkeypatch,
                                          tmp_path):
    """A prototype that cannot start degrades to cold spawn: warm_spawn
    returns None (never raises), records a degraded ``warm_fork`` event,
    and latches the manager so later spawns skip the broken plane."""
    monkeypatch.setattr(warm_fork.sys, "executable", "/bin/false")
    monkeypatch.setenv("RDT_WARM_FORK_WAIT_S", "2")
    ref = [None]
    proc = warm_fork.warm_spawn(ref, str(tmp_path), {},
                                str(tmp_path / "w0.log"), "w0")
    assert proc is None, "broken plane must cue the cold-spawn fallback"
    assert ref[0] is not None and not ref[0].available, \
        "first failure must latch the manager"
    evs = [e for e in metrics.events() if e["kind"] == "warm_fork"]
    assert any(e.get("degraded") and e.get("error") for e in evs)
    # latched: the second attempt short-circuits without touching /bin/false
    assert warm_fork.warm_spawn(ref, str(tmp_path), {},
                                str(tmp_path / "w1.log"), "w1") is None
    ref[0].stop()



def _refreshes() -> int:
    return int(metrics.snapshot()["counters"]
               .get("pool_warm_refreshes_total", {}).get("", 0))

def test_latched_plane_refreshes_and_forks_again(fast_prototype, monkeypatch,
                                                 tmp_path):
    """Supervised prototype restart (ROADMAP 4c): a latched-failed plane
    re-warms a fresh prototype on the next fork — latch → refresh →
    fork-fast-again — bounded by RDT_WARM_FORK_RETRIES, with the re-warm
    event and pool_warm_refreshes_total recording each restart."""
    monkeypatch.setenv("RDT_WARM_REFRESH_COOLDOWN_S", "0")
    monkeypatch.setenv("RDT_WARM_FORK_RETRIES", "2")
    monkeypatch.setenv("RDT_WARM_FORK_WAIT_S", "2")
    real_exe = warm_fork.sys.executable
    monkeypatch.setattr(warm_fork.sys, "executable", "/bin/false")
    mgr = warm_fork.WarmForkManager(str(tmp_path))
    try:
        with pytest.raises(warm_fork.WarmForkError):
            mgr.fork({}, str(tmp_path / "w0.log"), key="w0")
        assert mgr._failed, "broken prototype must latch the plane"
        # cooldown=0 + retries remaining: the plane advertises availability
        assert mgr.available, "refresh budget must keep the plane available"
        # heal the prototype binary; the next fork re-warms and succeeds
        monkeypatch.setattr(warm_fork.sys, "executable", real_exe)
        monkeypatch.setenv("RDT_WARM_FORK_WAIT_S", "10")
        child = mgr.fork({}, str(tmp_path / "w1.log"), key="w1")
        assert child.wait(timeout=15.0) == 1  # bootstrap-with-no-env exit
        assert not mgr._failed
        assert _refreshes() == 1
        evs = [e for e in metrics.events() if e["kind"] == "warm_fork"]
        assert any(e.get("rewarm") and e.get("refresh") == 1 for e in evs)
        # fork-fast-again: further forks ride the refreshed prototype
        c2 = mgr.fork({}, str(tmp_path / "w2.log"), key="w2")
        assert c2.wait(timeout=15.0) == 1
    finally:
        mgr.stop()


def test_refresh_budget_exhausts_to_permanent_latch(fast_prototype,
                                                    monkeypatch, tmp_path):
    """Exceeding RDT_WARM_FORK_RETRIES leaves the latch permanent: a plane
    that keeps crashing stops re-warming and every later fork cold-spawns."""
    monkeypatch.setenv("RDT_WARM_REFRESH_COOLDOWN_S", "0")
    monkeypatch.setenv("RDT_WARM_FORK_RETRIES", "1")
    monkeypatch.setenv("RDT_WARM_FORK_WAIT_S", "2")
    monkeypatch.setattr(warm_fork.sys, "executable", "/bin/false")
    mgr = warm_fork.WarmForkManager(str(tmp_path))
    try:
        with pytest.raises(warm_fork.WarmForkError):
            mgr.fork({}, str(tmp_path / "w0.log"), key="w0")
        # the one refresh attempt burns against the still-broken binary
        with pytest.raises(warm_fork.WarmForkError):
            mgr.fork({}, str(tmp_path / "w1.log"), key="w1")
        assert _refreshes() == 1
        assert not mgr.available, "exhausted refresh budget must latch"
        with pytest.raises(warm_fork.WarmForkError):
            mgr.fork({}, str(tmp_path / "w2.log"), key="w2")
        assert _refreshes() == 1
    finally:
        mgr.stop()


def test_fork_raise_fault_degrades_to_cold(fast_prototype, tmp_path):
    """The ``raise`` action at ``pool.fork`` models a transient fork-path
    fault: warm_spawn degrades to None and the caller cold-spawns, without
    latching the plane (the injected raise fires before the protocol)."""
    faults.clear()
    faults.inject("pool.fork", "raise", times=1)
    ref = [None]
    try:
        assert warm_fork.warm_spawn(ref, str(tmp_path), {},
                                    str(tmp_path / "w.log"), "w0") is None
        evs = [e for e in metrics.events() if e["kind"] == "warm_fork"]
        assert any(e.get("degraded") for e in evs)
    finally:
        faults.clear()
        if ref[0] is not None:
            ref[0].stop()
