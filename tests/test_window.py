"""Window functions (Spark parity surface: row_number/rank/dense_rank/
lag/lead + aggregates over a partition). Evaluation is distributed — rows
hash-shuffle by partition key and each bucket evaluates its whole partitions."""

import numpy as np
import pandas as pd

from raydp_tpu.etl import functions as F
from raydp_tpu.etl.window import Window


def _events(session, n=2000, users=13, parts=4):
    rng = np.random.RandomState(0)
    pdf = pd.DataFrame({
        "user": rng.randint(0, users, n),
        "ts": rng.permutation(n),
        "amount": rng.rand(n).round(4),
    })
    return pdf, session.createDataFrame(pdf, num_partitions=parts)


def test_row_number(session):
    pdf, df = _events(session)
    w = Window.partitionBy("user").orderBy("ts")
    out = df.withColumn("rn", F.row_number().over(w)).to_pandas()
    exp = pdf.copy()
    exp["rn"] = exp.sort_values("ts").groupby("user").cumcount() + 1
    merged = out.sort_values(["user", "ts"]).reset_index(drop=True)
    expected = exp.sort_values(["user", "ts"]).reset_index(drop=True)
    pd.testing.assert_frame_equal(merged, expected, check_dtype=False)


def test_rank_and_dense_rank_with_ties(session):
    rng = np.random.RandomState(1)
    pdf = pd.DataFrame({
        "k": rng.randint(0, 5, 600),
        "score": rng.randint(0, 10, 600),  # heavy ties
    })
    df = session.createDataFrame(pdf, num_partitions=4)
    w = Window.partitionBy("k").orderBy("score")
    out = (df.withColumn("r", F.rank().over(w))
             .withColumn("dr", F.dense_rank().over(w)).to_pandas())
    exp = pdf.copy()
    exp["r"] = exp.groupby("k")["score"].rank(method="min").astype(int)
    exp["dr"] = exp.groupby("k")["score"].rank(method="dense").astype(int)
    key = ["k", "score", "r", "dr"]
    pd.testing.assert_frame_equal(
        out[key].sort_values(key).reset_index(drop=True),
        exp[key].sort_values(key).reset_index(drop=True), check_dtype=False)


def test_lag_lead(session):
    pdf, df = _events(session, n=500, users=7)
    w = Window.partitionBy("user").orderBy("ts")
    out = (df.withColumn("prev", F.lag("amount", 1, -1.0).over(w))
             .withColumn("next", F.lead("amount", 1).over(w))
             .to_pandas().sort_values(["user", "ts"]).reset_index(drop=True))
    exp = pdf.sort_values(["user", "ts"]).reset_index(drop=True)
    g = exp.groupby("user")["amount"]
    exp["prev"] = g.shift(1).fillna(-1.0)
    exp["next"] = g.shift(-1)
    pd.testing.assert_frame_equal(out, exp, check_dtype=False)


def test_aggregate_over_partition(session):
    pdf, df = _events(session, n=800, users=9)
    w = Window.partitionBy("user")
    out = (df.withColumn("total", F.sum("amount").over(w))
             .withColumn("n", F.count("amount").over(w))
             .to_pandas())
    exp_total = pdf.groupby("user")["amount"].sum()
    exp_n = pdf.groupby("user")["amount"].count()
    for u in exp_total.index:
        rows = out[out["user"] == u]
        np.testing.assert_allclose(rows["total"], exp_total[u], rtol=1e-9)
        assert (rows["n"] == exp_n[u]).all()


def test_global_window_no_partition(session):
    pdf, df = _events(session, n=300, users=3)
    w = Window.orderBy("ts")
    out = df.withColumn("rn", F.row_number().over(w)).to_pandas()
    assert sorted(out["rn"]) == list(range(1, 301))
    # row numbers follow the global ts order
    assert (out.sort_values("ts")["rn"].to_numpy() == np.arange(1, 301)).all()


def test_window_replaces_existing_column(session):
    pdf, df = _events(session, n=200, users=4)
    w = Window.partitionBy("user").orderBy("ts")
    out = df.withColumn("amount2", F.lag("amount").over(w)) \
            .withColumn("amount2", F.lead("amount").over(w)).to_pandas()
    assert "amount2" in out.columns
    assert list(out.columns).count("amount2") == 1


def test_window_requires_order(session):
    import pytest

    with pytest.raises(ValueError, match="orderBy"):
        F.row_number().over(Window.partitionBy("user"))


def test_count_star_and_empty_bucket_types(session):
    """count("*") over a partition (the Spark-standard spelling) and string
    min over few distinct keys (some hash buckets empty — the empty-bucket
    output type must match the non-empty buckets, code-review r4)."""
    pdf = pd.DataFrame({
        "k": [1, 1, 2] * 50,
        "name": ["bb", "aa", "cc"] * 50,
        "v": list(range(150)),
    })
    df = session.createDataFrame(pdf, num_partitions=3)
    out = (df.withColumn("n", F.count("*").over(Window.partitionBy("k")))
             .withColumn("lo", F.min("name").over(Window.partitionBy("k")))
             .to_pandas())
    assert set(out[out["k"] == 1]["n"]) == {100}
    assert set(out[out["k"] == 2]["n"]) == {50}
    assert set(out[out["k"] == 1]["lo"]) == {"aa"}
    assert set(out[out["k"] == 2]["lo"]) == {"cc"}
    # integer sum keeps integer dtype even with empty buckets around
    out2 = df.withColumn("t", F.sum("v").over(Window.partitionBy("k")))
    assert pd.api.types.is_integer_dtype(out2.to_pandas()["t"])


def test_chained_window_columns_no_reexecution(session):
    """Chaining window columns must derive the schema statically — listing
    columns between the two withColumn calls must not execute the first
    window's shuffle (code-review r4)."""
    pdf, df = _events(session, n=300, users=4)
    w = Window.partitionBy("user").orderBy("ts")
    one = df.withColumn("rn", F.row_number().over(w))
    # schema known without running the plan
    assert one._schema is not None
    assert one.columns == ["user", "ts", "amount", "rn"]
    both = one.withColumn("prev", F.lag("amount").over(w))
    assert both._schema is not None
    out = both.to_pandas()
    assert {"rn", "prev"} <= set(out.columns)


def test_running_aggregate_with_order(session):
    """Spark's default frame WITH orderBy is unboundedPreceding..currentRow:
    sum over an ordered window is a RUNNING sum, and order-key ties share
    the frame (RANGE semantics) — verified against a pandas expanding sum
    with tie correction (code-review r4 finding)."""
    pdf = pd.DataFrame({
        "k": [1, 1, 1, 1, 2, 2, 2],
        "ts": [1, 2, 2, 3, 1, 2, 3],   # a tie at (k=1, ts=2)
        "x": [10.0, 20.0, 30.0, 40.0, 1.0, 2.0, 3.0],
    })
    df = session.createDataFrame(pdf, num_partitions=3)
    w = Window.partitionBy("k").orderBy("ts")
    out = (df.withColumn("run", F.sum("x").over(w))
             .withColumn("n", F.count("*").over(w))
             .to_pandas().sort_values(["k", "ts", "x"]).reset_index(drop=True))
    # k=1: rows ts=1→10; the ts=2 PEERS both see 10+20+30=60; ts=3→100
    assert out[out["k"] == 1]["run"].tolist() == [10.0, 60.0, 60.0, 100.0]
    assert out[out["k"] == 1]["n"].tolist() == [1, 3, 3, 4]
    assert out[out["k"] == 2]["run"].tolist() == [1.0, 3.0, 6.0]


def test_same_spec_windows_one_shuffle(session):
    """Adjacent window columns over the same partition keys must collapse to
    ONE shuffle (code-review r4): the compiled plan's map stage runs once."""
    pdf, df = _events(session, n=400, users=5)
    w = Window.partitionBy("user").orderBy("ts")
    both = (df.withColumn("rn", F.row_number().over(w))
              .withColumn("prev", F.lag("amount").over(w)))
    engine = session.engine
    from raydp_tpu.etl import tasks as T
    tasks, _ = engine._compile(both._plan, temps=[])
    # every reduce task carries BOTH window steps (one shuffle, chained eval)
    for t in tasks:
        kinds = [type(s).__name__ for s in t.steps]
        assert kinds.count("WindowStep") == 2, kinds
    out = both.to_pandas()
    exp = pdf.sort_values("ts").groupby("user").cumcount() + 1
    got = out.sort_values(["user", "ts"]).reset_index(drop=True)["rn"]
    assert got.tolist() == exp.loc[
        pdf.sort_values(["user", "ts"]).index].tolist()


def test_split_shards_fallback_shuffle_varies(session):
    """The more-ranks-than-blocks shard fallback must honor shuffle/seed:
    different seeds give different rank assignments, same seed is stable,
    and every variant keeps the equal-share invariant."""
    from raydp_tpu.data import from_frame

    ds = from_frame(_events(session, n=1000, users=3, parts=2)[1])
    a = ds.split_shards(world_size=5, shuffle=True, seed=1)
    b = ds.split_shards(world_size=5, shuffle=True, seed=1)
    c = ds.split_shards(world_size=5, shuffle=True, seed=2)
    assert a == b
    assert a != c
    for plans in (a, c):
        counts = [sum(n for _, _, n in p) for p in plans]
        assert counts == [200] * 5


def test_running_aggregate_ignores_nulls(session):
    """Spark ignores nulls inside the frame: a null row takes the prior
    running value (not null), an all-null prefix stays null, and a null tie
    peer does not poison the tie group (code-review r4 finding)."""
    pdf = pd.DataFrame({
        "k": [1, 1, 1, 2, 2, 2, 2],
        "ts": [1, 2, 3, 1, 2, 2, 3],
        "x": [None, None, 5.0, 10.0, None, 20.0, 30.0],
    })
    df = session.createDataFrame(pdf, num_partitions=2)
    w = Window.partitionBy("k").orderBy("ts")
    out = (df.withColumn("run", F.sum("x").over(w))
             .withColumn("avg", F.mean("x").over(w))
             .to_pandas().sort_values(["k", "ts", "x"], na_position="first")
             .reset_index(drop=True))
    k1 = out[out["k"] == 1]
    assert pd.isna(k1["run"].iloc[0]) and pd.isna(k1["run"].iloc[1])
    assert k1["run"].iloc[2] == 5.0
    k2 = out[out["k"] == 2]["run"].tolist()
    # ties at ts=2 (one null, one 20.0) both see 10+20=30
    assert k2 == [10.0, 30.0, 30.0, 60.0]
    assert out[out["k"] == 2]["avg"].tolist() == [10.0, 15.0, 15.0, 20.0]
