"""Window functions (Spark parity surface: row_number/rank/dense_rank/
lag/lead + aggregates over a partition). Evaluation is distributed — rows
hash-shuffle by partition key and each bucket evaluates its whole partitions."""

import numpy as np
import pandas as pd

from raydp_tpu.etl import functions as F
from raydp_tpu.etl.window import Window


def _events(session, n=2000, users=13, parts=4):
    rng = np.random.RandomState(0)
    pdf = pd.DataFrame({
        "user": rng.randint(0, users, n),
        "ts": rng.permutation(n),
        "amount": rng.rand(n).round(4),
    })
    return pdf, session.createDataFrame(pdf, num_partitions=parts)


def test_row_number(session):
    pdf, df = _events(session)
    w = Window.partitionBy("user").orderBy("ts")
    out = df.withColumn("rn", F.row_number().over(w)).to_pandas()
    exp = pdf.copy()
    exp["rn"] = exp.sort_values("ts").groupby("user").cumcount() + 1
    merged = out.sort_values(["user", "ts"]).reset_index(drop=True)
    expected = exp.sort_values(["user", "ts"]).reset_index(drop=True)
    pd.testing.assert_frame_equal(merged, expected, check_dtype=False)


def test_rank_and_dense_rank_with_ties(session):
    rng = np.random.RandomState(1)
    pdf = pd.DataFrame({
        "k": rng.randint(0, 5, 600),
        "score": rng.randint(0, 10, 600),  # heavy ties
    })
    df = session.createDataFrame(pdf, num_partitions=4)
    w = Window.partitionBy("k").orderBy("score")
    out = (df.withColumn("r", F.rank().over(w))
             .withColumn("dr", F.dense_rank().over(w)).to_pandas())
    exp = pdf.copy()
    exp["r"] = exp.groupby("k")["score"].rank(method="min").astype(int)
    exp["dr"] = exp.groupby("k")["score"].rank(method="dense").astype(int)
    key = ["k", "score", "r", "dr"]
    pd.testing.assert_frame_equal(
        out[key].sort_values(key).reset_index(drop=True),
        exp[key].sort_values(key).reset_index(drop=True), check_dtype=False)


def test_lag_lead(session):
    pdf, df = _events(session, n=500, users=7)
    w = Window.partitionBy("user").orderBy("ts")
    out = (df.withColumn("prev", F.lag("amount", 1, -1.0).over(w))
             .withColumn("next", F.lead("amount", 1).over(w))
             .to_pandas().sort_values(["user", "ts"]).reset_index(drop=True))
    exp = pdf.sort_values(["user", "ts"]).reset_index(drop=True)
    g = exp.groupby("user")["amount"]
    exp["prev"] = g.shift(1).fillna(-1.0)
    exp["next"] = g.shift(-1)
    pd.testing.assert_frame_equal(out, exp, check_dtype=False)


def test_aggregate_over_partition(session):
    pdf, df = _events(session, n=800, users=9)
    w = Window.partitionBy("user")
    out = (df.withColumn("total", F.sum("amount").over(w))
             .withColumn("n", F.count("amount").over(w))
             .to_pandas())
    exp_total = pdf.groupby("user")["amount"].sum()
    exp_n = pdf.groupby("user")["amount"].count()
    for u in exp_total.index:
        rows = out[out["user"] == u]
        np.testing.assert_allclose(rows["total"], exp_total[u], rtol=1e-9)
        assert (rows["n"] == exp_n[u]).all()


def test_global_window_no_partition(session):
    pdf, df = _events(session, n=300, users=3)
    w = Window.orderBy("ts")
    out = df.withColumn("rn", F.row_number().over(w)).to_pandas()
    assert sorted(out["rn"]) == list(range(1, 301))
    # row numbers follow the global ts order
    assert (out.sort_values("ts")["rn"].to_numpy() == np.arange(1, 301)).all()


def test_window_replaces_existing_column(session):
    pdf, df = _events(session, n=200, users=4)
    w = Window.partitionBy("user").orderBy("ts")
    out = df.withColumn("amount2", F.lag("amount").over(w)) \
            .withColumn("amount2", F.lead("amount").over(w)).to_pandas()
    assert "amount2" in out.columns
    assert list(out.columns).count("amount2") == 1


def test_window_requires_order(session):
    import pytest

    with pytest.raises(ValueError, match="orderBy"):
        F.row_number().over(Window.partitionBy("user"))


def test_count_star_and_empty_bucket_types(session):
    """count("*") over a partition (the Spark-standard spelling) and string
    min over few distinct keys (some hash buckets empty — the empty-bucket
    output type must match the non-empty buckets, code-review r4)."""
    pdf = pd.DataFrame({
        "k": [1, 1, 2] * 50,
        "name": ["bb", "aa", "cc"] * 50,
        "v": list(range(150)),
    })
    df = session.createDataFrame(pdf, num_partitions=3)
    out = (df.withColumn("n", F.count("*").over(Window.partitionBy("k")))
             .withColumn("lo", F.min("name").over(Window.partitionBy("k")))
             .to_pandas())
    assert set(out[out["k"] == 1]["n"]) == {100}
    assert set(out[out["k"] == 2]["n"]) == {50}
    assert set(out[out["k"] == 1]["lo"]) == {"aa"}
    assert set(out[out["k"] == 2]["lo"]) == {"cc"}
    # integer sum keeps integer dtype even with empty buckets around
    out2 = df.withColumn("t", F.sum("v").over(Window.partitionBy("k")))
    assert pd.api.types.is_integer_dtype(out2.to_pandas()["t"])


def test_chained_window_columns_no_reexecution(session):
    """Chaining window columns must derive the schema statically — listing
    columns between the two withColumn calls must not execute the first
    window's shuffle (code-review r4)."""
    pdf, df = _events(session, n=300, users=4)
    w = Window.partitionBy("user").orderBy("ts")
    one = df.withColumn("rn", F.row_number().over(w))
    # schema known without running the plan
    assert one._schema is not None
    assert one.columns == ["user", "ts", "amount", "rn"]
    both = one.withColumn("prev", F.lag("amount").over(w))
    assert both._schema is not None
    out = both.to_pandas()
    assert {"rn", "prev"} <= set(out.columns)
